"""BlockStore: raw-block ObjectStore — the BlueStore analog.

Model follows os/bluestore/BlueStore.cc semantics re-designed small:
object data lives in a single raw block file at allocator-assigned
extents; ALL metadata (onodes with per-block extent maps + checksums,
omap, collections, the free list, the deferred-write WAL) lives in the
KV tier (os/bluestore/BlueStore.h:413 Onode/Blob/Extent collapsed to a
min_alloc-granularity block map).  The KV commit is the transaction's
durability point, exactly like BlueStore's _kv_sync_thread:

  * big writes go copy-on-write to freshly allocated blocks, the device
    is flushed, THEN the KV commit swaps onode + freelist atomically —
    a crash in between leaves the old onode intact and the new blocks
    still free (no WAL needed, BlueStore's "new allocation" fast path);
  * small writes (<= deferred_max bytes) ride the KV commit itself as a
    deferred-WAL record (BlueStore.h:1169 TransContext STATE_WAL_QUEUED
    analog) and are applied to the block device after commit; mount
    replays any pending records (idempotent pwrites);
  * every min_alloc block carries a crc32c verified on read
    (BlueStore's per-blob csum); mismatch surfaces StoreError(EIO).

Divergence from the reference: clone copies blocks instead of
refcounting shared blobs (correctness-equivalent; COW sharing is a
space optimization), and the freelist is persisted as one coalesced
blob per commit rather than BitmapFreelistManager key-ranges — at this
store's scale the blob is tiny and the swap is atomic by construction.

Crash points (FaultSet `crash <prob> <site>` rules, seed-
deterministic, the ALICE torn-write model applied to KV commits and
extent writes):

  alloc.mid_cow       power loss partway through the COW extent
                      writes: a seeded prefix of one freshly
                      allocated block lands torn.  The committed
                      onode still points at the OLD block, so a
                      remount reads old content whole — never an
                      interleave.
  wal.pre_kv_commit   the KV commit itself is torn: a seeded prefix
                      (or, with an fsync_reorder rule armed, a
                      seeded SUBSET) of the KV transaction's ops
                      land.  Mount verifies freelist-vs-onode
                      consistency and repairs overlaps.
  wal.post_kv_commit  KV commit durable, the deferred-write device
                      applies never ran; mount replays the WAL
                      records (this replaces the old
                      debug_skip_deferred_apply test hook).
  wal.mid_apply       power loss partway through applying deferred
                      WAL writes to the device (one extent torn
                      mid-block); replay rewrites them whole.
  wal.pre_trim        applied + fsync'd, crash before the WAL
                      records are removed from the KV; replay is
                      idempotent.

With an `fsync_reorder` FaultSet rule armed, a crash additionally
rolls back a seeded SUBSET of the device writes buffered since the
last fsync barrier (deferred WAL applies ride un-fsync'd for up to
WAL_FLUSH_EVERY commits) — durable B, lost earlier A — and mount
replay must still repair every acked write bit-exact.
"""

from __future__ import annotations

import os
import threading
from typing import Iterable

from ..kv.keyvaluedb import KeyValueDB, KVTransaction
from ..kv.memdb import MemDB
from ..kv.sqlitedb import SqliteDB
from ..ops.crc32c import crc32c
from ..utils import denc
from .objectstore import (EEXIST, EIO, ENOENT, ObjectStore, StoreError,
                          Transaction)

MIN_ALLOC = 4096               # bluestore_min_alloc_size
DEFERRED_MAX = 64 * 1024       # writes at or under this ride the KV WAL
GROW = 256 * MIN_ALLOC         # device growth increment (1 MiB)
WAL_FLUSH_EVERY = 16           # applied WAL records kept before trim

P_SUPER = "S"
P_COLL = "C"
P_ONODE = "O"
P_OMAP = "M"
P_WAL = "W"


def _okey(cid: str, oid: str) -> str:
    return f"{cid}/{oid}"


class ExtentAllocator:
    """Coalesced free-extent list with first-fit block allocation
    (StupidAllocator's role, os/bluestore/StupidAllocator.cc)."""

    def __init__(self, extents: list[list[int]] | None = None):
        # sorted, non-adjacent [offset, length] runs
        self.free: list[list[int]] = [list(e) for e in (extents or [])]

    def dump(self) -> list[list[int]]:
        return [list(e) for e in self.free]

    def total_free(self) -> int:
        return sum(l for _, l in self.free)

    def allocate(self, nbytes: int) -> list[tuple[int, int]]:
        """Take nbytes (MIN_ALLOC-aligned) of space, possibly split
        across runs; raises if the device must grow first."""
        assert nbytes % MIN_ALLOC == 0
        got: list[tuple[int, int]] = []
        need = nbytes
        i = 0
        while need and i < len(self.free):
            off, length = self.free[i]
            take = min(length, need)
            got.append((off, take))
            need -= take
            if take == length:
                self.free.pop(i)
            else:
                self.free[i][0] += take
                self.free[i][1] -= take
                i += 1
        if need:
            # put partial grabs back and fail up to the caller (grow)
            self.release(got)
            raise MemoryError(f"allocator short {need} bytes")
        return got

    def allocate_at(self, off: int, length: int) -> bool:
        """Carve a SPECIFIC range out of the free list (mount-time
        freelist repair); False if the range is not wholly free."""
        for i, (roff, rlen) in enumerate(self.free):
            if roff <= off and off + length <= roff + rlen:
                self.free.pop(i)
                if off > roff:
                    self._insert(roff, off - roff)
                if off + length < roff + rlen:
                    self._insert(off + length, roff + rlen - off - length)
                return True
        return False

    def release(self, extents: Iterable[tuple[int, int]]) -> None:
        for off, length in extents:
            if not length:
                continue
            self._insert(off, length)

    def _insert(self, off: int, length: int) -> None:
        lo, hi = 0, len(self.free)
        while lo < hi:
            mid = (lo + hi) // 2
            if self.free[mid][0] < off:
                lo = mid + 1
            else:
                hi = mid
        self.free.insert(lo, [off, length])
        # coalesce with neighbours
        if lo + 1 < len(self.free) and \
                self.free[lo][0] + self.free[lo][1] == self.free[lo + 1][0]:
            self.free[lo][1] += self.free[lo + 1][1]
            self.free.pop(lo + 1)
        if lo > 0 and \
                self.free[lo - 1][0] + self.free[lo - 1][1] == self.free[lo][0]:
            self.free[lo - 1][1] += self.free[lo][1]
            self.free.pop(lo)


class _Device:
    """The raw block "device": a file (or a bytearray for path-less
    test stores), pread/pwrite/flush — KernelDevice.cc's role."""

    def __init__(self, path: str):
        self.path = path
        self._f = None
        self._mem = bytearray() if not path else None
        self.size = 0

    def create(self) -> None:
        if self.path:
            with open(self.path, "wb"):
                pass
        self.open()

    def open(self) -> None:
        if self.path:
            if self._f is not None:
                self._f.close()    # mkfs-then-mount must not leak one
            self._f = open(self.path, "r+b")
            self._f.seek(0, os.SEEK_END)
            self.size = self._f.tell()
        else:
            self.size = len(self._mem)

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

    def grow(self, new_size: int) -> None:
        if new_size <= self.size:
            return
        if self._f is not None:
            self._f.truncate(new_size)
        else:
            self._mem.extend(b"\x00" * (new_size - len(self._mem)))
        self.size = new_size

    def pwrite(self, off: int, data: bytes) -> None:
        if self._f is not None:
            self._f.seek(off)
            self._f.write(data)
        else:
            self._mem[off: off + len(data)] = data

    def pread(self, off: int, length: int) -> bytes:
        if self._f is not None:
            self._f.seek(off)
            return self._f.read(length)
        return bytes(self._mem[off: off + length])

    def flush(self) -> None:
        if self._f is not None:
            self._f.flush()
            os.fsync(self._f.fileno())


class BlockStore(ObjectStore):
    """Onode format (P_ONODE, denc): {"size", "xattrs",
    "blocks": {block#: [poff, crc32c]}} — absent block# = hole."""

    def __init__(self, path: str = "", deferred_max: int = DEFERRED_MAX):
        super().__init__()
        self.path = path
        self.deferred_max = deferred_max
        self.db: KeyValueDB = SqliteDB(f"{path}/db") if path else MemDB()
        self.dev = _Device(f"{path}/block" if path else "")
        self.alloc = ExtentAllocator()
        self._lock = threading.RLock()
        self._wal_seq = 0
        self._wal_applied: list[str] = []   # applied, not yet trimmed
        self._wal_poffs: set[int] = set()   # extents those records target
        # device writes since the last fsync barrier, with pre-images,
        # recorded only while crash rules are installed: the
        # fsync-reordering model rolls a seeded subset of them back at
        # crash time (durable B, lost earlier A)
        self._unflushed: list[tuple[int, bytes]] = []
        self.counters = {
            "wal_records_replayed": 0,
            "wal_torn_extent_repairs": 0,
            "freelist_repairs": 0,
            "fsync_reorder_windows": 0,
        }

    def journal_stats(self) -> dict:
        return dict(self.counters)

    def crash_sites(self) -> list[str]:
        return ["wal.pre_kv_commit", "wal.post_kv_commit",
                "wal.mid_apply", "wal.pre_trim", "alloc.mid_cow",
                "store.pre_apply", "store.post_apply", "pglog.append"]

    # -- lifecycle ---------------------------------------------------------

    def mkfs(self) -> None:
        if self.path:
            os.makedirs(self.path, exist_ok=True)
            self.db = SqliteDB(f"{self.path}/db")
        self.db.open()
        self.dev.create()
        kvt = self.db.transaction()
        kvt.set(P_SUPER, "super", denc.dumps(
            {"min_alloc": MIN_ALLOC, "dev_size": 0}))
        kvt.set(P_SUPER, "freelist", denc.dumps([]))
        self.db.submit_transaction(kvt, sync=True)

    def mount(self) -> None:
        if self.path and not os.path.exists(f"{self.path}/db"):
            raise FileNotFoundError(f"{self.path}/db")
        self.db.open()
        blob = self.db.get(P_SUPER, "super")
        if blob is None:
            raise StoreError(EIO, "no blockstore superblock")
        super_ = denc.loads(blob)
        self.dev.open()
        # the file may be shorter than the committed dev_size if a grow
        # raced a crash; extend (zeros are fine, blocks are COW)
        self.dev.grow(super_["dev_size"])
        self.alloc = ExtentAllocator(
            denc.loads(self.db.get(P_SUPER, "freelist")))
        self._replay_wal()
        self._verify_freelist()

    def umount(self) -> None:
        if not self.frozen:
            self._flush_deferred()
        self.dev.close()
        self.db.close()

    # -- crash plane -------------------------------------------------------

    def _crash_tracking(self) -> bool:
        from ..utils import faults
        return faults.get().crash_tracking_armed(self.owner)

    def _dev_write(self, poff: int, data: bytes) -> None:
        """All device mutation funnels through here so the reordering
        model can roll un-fsync'd writes back at crash time."""
        if self._crash_tracking():
            self._unflushed.append(
                (poff, self.dev.pread(poff, len(data))))
        self.dev.pwrite(poff, data)

    def _dev_flush(self) -> None:
        """fsync barrier: everything buffered is durable now."""
        self.dev.flush()
        self._unflushed = []

    def _panic(self, site: str) -> None:
        """On simulated power loss, first settle which un-fsync'd
        device writes actually survived: with an fsync_reorder rule
        armed, a seeded SUBSET survives (out-of-order durability) —
        the rest are rolled back to their pre-images."""
        self._apply_crash_reorder()
        super()._panic(site)

    def _apply_crash_reorder(self) -> None:
        from ..utils import faults
        fs = faults.get()
        if not self._unflushed or not fs.reorder_armed(self.owner):
            self._unflushed = []
            return
        mask = fs.torn_survivors(self.owner, len(self._unflushed))
        for (poff, pre), survives in zip(self._unflushed, mask):
            if not survives:
                self.dev.pwrite(poff, pre)
        self.dev.flush()
        self._unflushed = []
        self.counters["fsync_reorder_windows"] += 1

    def _torn_extent_crash(self, site: str,
                           writes: dict[int, bytes]) -> None:
        """Power loss mid-way through a batch of extent writes: a
        seeded number of them land whole, one more lands TORN (a
        prefix of the block), the rest never reach the device."""
        from ..utils import faults
        fs = faults.get()
        items = list(writes.items())
        k = int(fs.torn_keep_fraction(self.owner) * len(items))
        for poff, data in items[:k]:
            self._dev_write(poff, data)
        if k < len(items):
            poff, data = items[k]
            keep = int(fs.torn_keep_fraction(self.owner) * len(data))
            self._dev_write(poff, data[:keep])
        self._panic(site)

    def _maybe_crash_torn_kv(self, site: str, kvt: KVTransaction) -> None:
        """The ALICE torn-write model applied to the KV commit: a
        seeded prefix (or, under the reordering model, a seeded
        subset) of the transaction's ops land as a committed torn
        transaction, then the store dies.  Mount-time freelist
        verification repairs the inconsistent window."""
        from ..utils import faults
        fs = faults.get()
        if not fs.should_crash(self.owner, site):
            return
        ops, reordered = fs.torn_ops(self.owner, kvt.ops)
        if reordered:
            self.counters["fsync_reorder_windows"] += 1
        part = self.db.transaction()
        part.ops = ops
        self.db.submit_transaction(part, sync=True)
        self._panic(site)

    # -- deferred WAL ------------------------------------------------------

    def _replay_wal(self) -> None:
        """Re-apply every pending deferred write (idempotent: targets
        are extents owned by the committed onodes).  A target whose
        on-disk bytes don't already match the record — torn mid-apply,
        lost to an fsync-reorder window, or never applied at all — is
        a repair and counted."""
        pending = list(self.db.iterate(P_WAL, ""))
        for _key, blob in pending:
            for poff, data in denc.loads(blob)["writes"]:
                if self.dev.pread(poff, len(data)) != data:
                    self.counters["wal_torn_extent_repairs"] += 1
                self.dev.pwrite(poff, data)
            self.counters["wal_records_replayed"] += 1
        if pending:
            self.dev.flush()
            kvt = self.db.transaction()
            for key, _ in pending:
                kvt.rmkey(P_WAL, key)
            self.db.submit_transaction(kvt, sync=True)
        self._wal_applied = []
        self._wal_poffs = set()
        self._unflushed = []

    def _verify_freelist(self) -> None:
        """Mount-time consistency pass: a torn KV commit can land an
        onode without its freelist swap (or vice versa), leaving a
        block both referenced and free — the next allocation would
        then overwrite live data.  Carve every referenced extent out
        of the free list (count repairs); leaked-but-unreferenced
        blocks are merely lost space, never corruption."""
        referenced: set[int] = set()
        for _key, blob in self.db.iterate(P_ONODE, ""):
            for poff, _csum in denc.loads(blob)["blocks"].values():
                referenced.add(poff)
        overlaps = [poff for poff in sorted(referenced)
                    if self._freelist_contains(poff)]
        for poff in overlaps:
            ext = self.alloc.allocate_at(poff, MIN_ALLOC)
            if ext:
                self.counters["freelist_repairs"] += 1
        if overlaps:
            kvt = self.db.transaction()
            kvt.set(P_SUPER, "freelist", denc.dumps(self.alloc.dump()))
            self.db.submit_transaction(kvt, sync=True)

    def _freelist_contains(self, poff: int) -> bool:
        for off, length in self.alloc.free:
            if off <= poff < off + length:
                return True
        return False

    def _flush_deferred(self) -> None:
        """fsync the device, then drop applied WAL records — they are
        no longer needed for crash recovery."""
        if not self._wal_applied:
            return
        self._dev_flush()
        # crash site: device durable, WAL records not yet trimmed —
        # mount must replay them idempotently
        self._maybe_crash("wal.pre_trim")
        kvt = self.db.transaction()
        for key in self._wal_applied:
            kvt.rmkey(P_WAL, key)
        self.db.submit_transaction(kvt, sync=True)
        self._wal_applied = []
        self._wal_poffs = set()

    # -- transaction application ------------------------------------------

    def _do_transaction(self, txn: Transaction) -> None:
        with self._lock:
            st = {
                "onodes": {},       # okey -> head dict | None
                "omaps": {},        # "cid/oid/k" -> bytes | None
                "new_colls": set(),
                "kvt": self.db.transaction(),
                "pending": {},      # poff -> block bytes (this txn)
                "direct": {},       # poff -> data, write-before-commit
                "wal": {},          # poff -> data, rides the KV commit
                "allocated": [],    # rollback on failure
                "freed": [],        # released only at commit
            }
            try:
                for op in txn.ops:
                    self._apply_op(op, st)
            except BaseException:
                self.alloc.release(st["allocated"])
                raise
            self._commit(st)

    def _commit(self, st: dict) -> None:
        self._check_frozen()     # crashed: no device or KV write lands
        # traced: the wal span covers COW extent writes + the KV
        # commit + deferred applies — the BlockStore durability cost
        # a write pays, the journal-span analog for this backend
        from ..utils import optracker
        with optracker.span("wal"):
            self._commit_traced(st)

    def _commit_traced(self, st: dict) -> None:
        kvt: KVTransaction = st["kvt"]
        # If a freed extent is still the target of an untrimmed WAL
        # record, trim the WAL first — otherwise a crash after the
        # extent is reused would replay stale bytes over live data
        # (BlueStore sequences deferred txns against reuse the same way).
        if any(off in self._wal_poffs for off, _l in st["freed"]):
            self._flush_deferred()
        # frees take effect with this commit; no further allocations
        # happen in this txn, so in-memory release is safe now
        self.alloc.release(st["freed"])
        if st["direct"]:
            # crash site: power loss mid-way through the COW extent
            # writes — one block lands torn, but the committed onode
            # still points at the old block (old-or-new, never a mix)
            from ..utils import faults
            if faults.get().should_crash(self.owner, "alloc.mid_cow"):
                self._torn_extent_crash("alloc.mid_cow", st["direct"])
            for poff, data in st["direct"].items():
                self._dev_write(poff, data)
            self._dev_flush()
        wal_key = None
        if st["wal"]:
            self._wal_seq += 1
            wal_key = f"{self._wal_seq:016x}"
            kvt.set(P_WAL, wal_key,
                    denc.dumps(
                        {"writes": [[o, d] for o, d in st["wal"].items()]}))
        for okey, head in st["onodes"].items():
            if head is None:
                kvt.rmkey(P_ONODE, okey)
            else:
                kvt.set(P_ONODE, okey, denc.dumps(head))
        for key, val in st["omaps"].items():
            if val is None:
                kvt.rmkey(P_OMAP, key)
            else:
                kvt.set(P_OMAP, key, val)
        kvt.set(P_SUPER, "freelist", denc.dumps(self.alloc.dump()))
        kvt.set(P_SUPER, "super", denc.dumps(
            {"min_alloc": MIN_ALLOC, "dev_size": self.dev.size}))
        # crash site: the KV commit itself tears — a seeded prefix (or
        # reordered subset) of its ops land; mount repairs
        self._maybe_crash_torn_kv("wal.pre_kv_commit", kvt)
        self.db.submit_transaction(kvt, sync=True)
        # ---- commit point ----
        if st["wal"]:
            # crash site: KV durable (the txn is committed), deferred
            # device applies never run — mount replays the WAL record
            self._maybe_crash("wal.post_kv_commit")
            from ..utils import faults
            if faults.get().should_crash(self.owner, "wal.mid_apply"):
                # crash site: power loss partway through the deferred
                # applies, one extent torn mid-block; replay rewrites
                self._torn_extent_crash("wal.mid_apply", st["wal"])
            for poff, data in st["wal"].items():
                self._dev_write(poff, data)
            self._wal_applied.append(wal_key)
            self._wal_poffs.update(st["wal"])
            if len(self._wal_applied) >= WAL_FLUSH_EVERY:
                self._flush_deferred()

    # -- allocation helpers ------------------------------------------------

    def _allocate_block(self, st: dict) -> int:
        try:
            ext = self.alloc.allocate(MIN_ALLOC)
        except MemoryError:
            new_size = self.dev.size + GROW
            self.alloc.release([(self.dev.size, GROW)])
            self.dev.grow(new_size)
            ext = self.alloc.allocate(MIN_ALLOC)
        st["allocated"].extend(ext)
        return ext[0][0]

    # -- onode helpers -----------------------------------------------------

    def _load_onode(self, st: dict, cid: str, oid: str):
        okey = _okey(cid, oid)
        if okey in st["onodes"]:
            return st["onodes"][okey]
        blob = self.db.get(P_ONODE, okey)
        head = denc.loads(blob) if blob is not None else None
        st["onodes"][okey] = head
        return head

    def _onode(self, st: dict, cid: str, oid: str, create: bool) -> dict:
        head = self._load_onode(st, cid, oid)
        if head is None:
            if not create:
                raise StoreError(ENOENT, f"no object {cid}/{oid}")
            if cid not in st["new_colls"] and \
                    self.db.get(P_COLL, cid) is None:
                raise StoreError(ENOENT, f"no collection {cid}")
            head = {"size": 0, "xattrs": {}, "blocks": {}}
            st["onodes"][_okey(cid, oid)] = head
        return head

    def _read_block_raw(self, st: dict, head: dict, blk: int) -> bytes:
        """Current content of a logical block through the txn overlay.
        Device reads ARE csum-verified: an RMW merge over silently
        corrupt bytes would otherwise re-seal them under a fresh valid
        crc and launder the corruption past every future read."""
        ent = head["blocks"].get(blk)
        if ent is None:
            return b""
        poff, csum = ent
        if poff in st["pending"]:
            return st["pending"][poff]
        data = self.dev.pread(poff, MIN_ALLOC)
        if crc32c(0, data) != csum:
            raise StoreError(EIO, f"csum mismatch reading block {blk} "
                                  f"at {poff:#x} for rmw")
        return data

    def _put_block(self, st: dict, head: dict, blk: int,
                   data: bytes, deferred: bool) -> None:
        """COW one logical block: allocate, stage the device write,
        point the onode at it, free the old block."""
        assert len(data) <= MIN_ALLOC
        old = head["blocks"].get(blk)
        if old is not None:
            self._free_block(st, old[0])
        if len(data) < MIN_ALLOC:
            data = data + b"\x00" * (MIN_ALLOC - len(data))
        poff = self._allocate_block(st)
        head["blocks"][blk] = [poff, crc32c(0, data)]
        st["pending"][poff] = data
        (st["wal"] if deferred else st["direct"])[poff] = data

    def _free_block(self, st: dict, poff: int) -> None:
        st["freed"].append((poff, MIN_ALLOC))
        st["pending"].pop(poff, None)
        st["direct"].pop(poff, None)    # a same-txn write to a block we
        st["wal"].pop(poff, None)       # just freed must not hit disk

    def _drop_block(self, st: dict, head: dict, blk: int) -> None:
        ent = head["blocks"].pop(blk, None)
        if ent is not None:
            self._free_block(st, ent[0])

    def _write_span(self, st: dict, head: dict, offset: int,
                    data: bytes, zero: bool = False) -> None:
        deferred = len(data) <= self.deferred_max
        pos = 0
        while pos < len(data):
            blk = (offset + pos) // MIN_ALLOC
            boff = (offset + pos) % MIN_ALLOC
            take = min(len(data) - pos, MIN_ALLOC - boff)
            chunk = data[pos: pos + take]
            if zero and take == MIN_ALLOC:
                self._drop_block(st, head, blk)     # punch a hole
            else:
                if take == MIN_ALLOC:
                    merged = chunk
                else:
                    cur = bytearray(self._read_block_raw(st, head, blk))
                    if len(cur) < boff + take:
                        cur.extend(b"\x00" * (boff + take - len(cur)))
                    cur[boff: boff + take] = chunk
                    merged = bytes(cur)
                if zero and not any(merged):
                    self._drop_block(st, head, blk)
                else:
                    self._put_block(st, head, blk, merged, deferred)
            pos += take

    def _purge(self, st: dict, cid: str, oid: str) -> None:
        head = self._load_onode(st, cid, oid)
        if head is not None:
            for blk in list(head["blocks"]):
                self._drop_block(st, head, blk)
        st["onodes"][_okey(cid, oid)] = None
        for k in self._omap_items(st, cid, oid):
            st["omaps"][f"{cid}/{oid}/{k}"] = None

    def _copy_object(self, st: dict, src_head: dict, dcid: str,
                     doid: str, omap: dict[str, bytes]) -> None:
        self._purge(st, dcid, doid)
        new = {"size": src_head["size"],
               "xattrs": dict(src_head["xattrs"]), "blocks": {}}
        st["onodes"][_okey(dcid, doid)] = new
        # deferred-vs-direct follows the TOTAL copied size, or a large
        # clone would smuggle its whole body into one KV WAL record
        deferred = src_head["size"] <= self.deferred_max
        for blk in sorted(src_head["blocks"]):
            data = self._read_block_raw(st, src_head, blk)
            self._put_block(st, new, blk, data, deferred=deferred)
        for k, val in omap.items():
            st["omaps"][f"{dcid}/{doid}/{k}"] = val

    def _omap_items(self, st: dict, cid: str, oid: str) -> dict[str, bytes]:
        prefix = f"{cid}/{oid}/"
        out = {}
        for key, val in self.db.iterate(P_OMAP, prefix):
            if not key.startswith(prefix):
                break
            out[key[len(prefix):]] = val
        for key, val in st["omaps"].items():
            if key.startswith(prefix):
                k = key[len(prefix):]
                if val is None:
                    out.pop(k, None)
                else:
                    out[k] = val
        return out

    # -- op dispatch -------------------------------------------------------

    def _apply_op(self, op: tuple, st: dict) -> None:
        kind = op[0]
        if kind == "mkcoll":
            _, cid = op
            if self.db.get(P_COLL, cid) is not None or \
                    cid in st["new_colls"]:
                raise StoreError(EEXIST, f"collection {cid} exists")
            st["new_colls"].add(cid)
            st["kvt"].set(P_COLL, cid, b"1")
        elif kind == "rmcoll":
            _, cid = op
            st["kvt"].rmkey(P_COLL, cid)
            st["new_colls"].discard(cid)
            # committed objects
            for key, _v in list(self.db.iterate(P_ONODE, f"{cid}/")):
                if not key.startswith(f"{cid}/"):
                    break
                oid = key[len(cid) + 1:]
                self._purge(st, cid, oid)
            # objects staged earlier in this same txn
            for key in [k for k, h in st["onodes"].items()
                        if h is not None and k.startswith(f"{cid}/")]:
                self._purge(st, cid, key[len(cid) + 1:])
        elif kind == "touch":
            self._onode(st, op[1], op[2], create=True)
        elif kind == "write":
            _, cid, oid, offset, data = op
            head = self._onode(st, cid, oid, create=True)
            self._write_span(st, head, offset, data)
            head["size"] = max(head["size"], offset + len(data))
        elif kind == "zero":
            _, cid, oid, offset, length = op
            head = self._onode(st, cid, oid, create=True)
            self._write_span(st, head, offset, b"\x00" * length, zero=True)
            head["size"] = max(head["size"], offset + length)
        elif kind == "truncate":
            _, cid, oid, size = op
            head = self._onode(st, cid, oid, create=True)
            if size < head["size"]:
                first_dead = (size + MIN_ALLOC - 1) // MIN_ALLOC
                for blk in [b for b in head["blocks"] if b >= first_dead]:
                    self._drop_block(st, head, blk)
                if size % MIN_ALLOC:
                    blk = size // MIN_ALLOC
                    if blk in head["blocks"]:
                        cur = self._read_block_raw(st, head, blk)
                        kept = cur[: size % MIN_ALLOC]
                        if any(kept):
                            self._put_block(
                                st, head, blk, kept,
                                deferred=len(kept) <= self.deferred_max)
                        else:
                            self._drop_block(st, head, blk)
            head["size"] = size
        elif kind in ("remove", "try_remove"):
            _, cid, oid = op
            if self._load_onode(st, cid, oid) is None:
                if kind == "remove":
                    raise StoreError(ENOENT, f"remove {cid}/{oid}")
                return
            self._purge(st, cid, oid)
        elif kind in ("clone", "try_clone"):
            _, cid, src, dst = op
            src_head = self._load_onode(st, cid, src)
            if src_head is None:
                if kind == "try_clone":
                    return
                raise StoreError(ENOENT, f"clone src {cid}/{src}")
            omap = self._omap_items(st, cid, src)
            self._copy_object(st, src_head, cid, dst, omap)
        elif kind == "move":
            _, scid, soid, dcid, doid = op
            src_head = self._load_onode(st, scid, soid)
            if src_head is None:
                raise StoreError(ENOENT, f"move src {scid}/{soid}")
            if dcid not in st["new_colls"] and \
                    self.db.get(P_COLL, dcid) is None:
                raise StoreError(ENOENT, f"no collection {dcid}")
            omap = self._omap_items(st, scid, soid)
            self._copy_object(st, src_head, dcid, doid, omap)
            self._purge(st, scid, soid)
        elif kind == "setattr":
            _, cid, oid, name, value = op
            self._onode(st, cid, oid, create=True)["xattrs"][name] = value
        elif kind == "rmattr":
            _, cid, oid, name = op
            self._onode(st, cid, oid, create=False)["xattrs"].pop(name, None)
        elif kind == "omap_set":
            _, cid, oid, kvs = op
            self._onode(st, cid, oid, create=True)
            for k, v in kvs.items():
                st["omaps"][f"{cid}/{oid}/{k}"] = v
        elif kind == "omap_rm":
            _, cid, oid, keys = op
            self._onode(st, cid, oid, create=False)
            for k in keys:
                st["omaps"][f"{cid}/{oid}/{k}"] = None
        elif kind == "omap_clear":
            _, cid, oid = op
            self._onode(st, cid, oid, create=False)
            for k in self._omap_items(st, cid, oid):
                st["omaps"][f"{cid}/{oid}/{k}"] = None
        else:
            raise StoreError(22, f"blockstore: unknown op {kind!r}")

    # -- reads -------------------------------------------------------------

    def _committed_onode(self, cid: str, oid: str) -> dict:
        blob = self.db.get(P_ONODE, _okey(cid, oid))
        if blob is None:
            raise StoreError(ENOENT, f"no object {cid}/{oid}")
        return denc.loads(blob)

    def read(self, cid: str, oid: str, offset: int = 0,
             length: int = 0) -> bytes:
        self._maybe_eio(oid)
        with self._lock:
            head = self._committed_onode(cid, oid)
            size = head["size"]
            if length == 0:
                length = max(0, size - offset)
            end = min(offset + length, size)
            if end <= offset:
                return b""
            out = bytearray()
            pos = offset
            while pos < end:
                blk = pos // MIN_ALLOC
                boff = pos % MIN_ALLOC
                take = min(end - pos, MIN_ALLOC - boff)
                ent = head["blocks"].get(blk)
                if ent is None:
                    out.extend(b"\x00" * take)
                else:
                    poff, csum = ent
                    data = self.dev.pread(poff, MIN_ALLOC)
                    if crc32c(0, data) != csum:
                        raise StoreError(
                            EIO, f"csum mismatch {cid}/{oid} block {blk}")
                    out.extend(data[boff: boff + take])
                pos += take
            return bytes(out)

    def stat(self, cid: str, oid: str) -> dict:
        with self._lock:
            return {"size": self._committed_onode(cid, oid)["size"]}

    def exists(self, cid: str, oid: str) -> bool:
        with self._lock:
            return self.db.get(P_ONODE, _okey(cid, oid)) is not None

    def getattr(self, cid: str, oid: str, name: str) -> bytes:
        with self._lock:
            xattrs = self._committed_onode(cid, oid)["xattrs"]
            if name not in xattrs:
                raise StoreError(ENOENT, f"no xattr {name}")
            return xattrs[name]

    def getattrs(self, cid: str, oid: str) -> dict[str, bytes]:
        with self._lock:
            return dict(self._committed_onode(cid, oid)["xattrs"])

    def omap_get(self, cid: str, oid: str) -> dict[str, bytes]:
        with self._lock:
            self._committed_onode(cid, oid)
            prefix = f"{cid}/{oid}/"
            out = {}
            for key, val in self.db.iterate(P_OMAP, prefix):
                if not key.startswith(prefix):
                    break
                out[key[len(prefix):]] = val
            return out

    def omap_get_values(self, cid: str, oid: str,
                        keys: Iterable[str]) -> dict[str, bytes]:
        omap = self.omap_get(cid, oid)
        return {k: omap[k] for k in keys if k in omap}

    def list_collections(self) -> list[str]:
        with self._lock:
            return sorted(k for k, _ in self.db.iterate(P_COLL, ""))

    def collection_exists(self, cid: str) -> bool:
        with self._lock:
            return self.db.get(P_COLL, cid) is not None

    def collection_list(self, cid: str, start: str = "",
                        max_count: int = 0) -> list[str]:
        with self._lock:
            if self.db.get(P_COLL, cid) is None:
                raise StoreError(ENOENT, f"no collection {cid}")
            prefix = f"{cid}/"
            names = []
            # seed the iterator at the resume point, or paging a big
            # collection (backfill/scrub) rescans from the front each
            # page — O(N^2/k) over the whole scan
            for key, _v in self.db.iterate(P_ONODE, prefix + start):
                if not key.startswith(prefix):
                    break
                name = key[len(prefix):]
                if name > start:
                    names.append(name)
                    if max_count and len(names) >= max_count:
                        break
            return names
