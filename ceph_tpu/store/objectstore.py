"""Transaction model + abstract ObjectStore.

Transaction op set follows os/ObjectStore.h:1041 ff (touch, write, zero,
truncate, remove, setattrs, rmattr, clone, omap ops, collection ops);
queue_transactions (:1453) applies asynchronously and fires on_applied /
on_commit callbacks, apply_transactions (:1429) is the synchronous
wrapper.  Object identity is (collection, object-name); sort order of
object names is the PG-scan order used by backfill and scrub.
"""

from __future__ import annotations

import abc
import threading
from typing import Callable, Iterable

from ..utils.bufferlist import as_buffer
from ..utils.faults import CrashPoint

ENOENT = 2
EEXIST = 17
EIO = 5


class StoreError(Exception):
    def __init__(self, errno_: int, msg: str = ""):
        super().__init__(msg or f"errno {errno_}")
        self.errno = errno_


class Transaction:
    """An ordered list of mutations applied atomically."""

    def __init__(self):
        self.ops: list[tuple] = []
        self.on_applied: list[Callable] = []
        self.on_commit: list[Callable] = []

    # -- collection ops ----------------------------------------------------

    def create_collection(self, cid: str) -> "Transaction":
        self.ops.append(("mkcoll", cid))
        return self

    def remove_collection(self, cid: str) -> "Transaction":
        self.ops.append(("rmcoll", cid))
        return self

    # -- object data ops ---------------------------------------------------

    def touch(self, cid: str, oid: str) -> "Transaction":
        self.ops.append(("touch", cid, oid))
        return self

    def write(self, cid: str, oid: str, offset: int,
              data) -> "Transaction":
        """`data` may be bytes, a memoryview (e.g. a shard view over
        the EC encode output), or a BufferList rope — kept AS A VIEW:
        backends consume the buffer protocol directly, and journaled
        stores flatten exactly once at WAL-append time (the denc
        serialize).  A multi-segment rope is the only case that
        flattens here (audited)."""
        self.ops.append(("write", cid, oid, offset, as_buffer(data)))
        return self

    def zero(self, cid: str, oid: str, offset: int,
             length: int) -> "Transaction":
        self.ops.append(("zero", cid, oid, offset, length))
        return self

    def truncate(self, cid: str, oid: str, size: int) -> "Transaction":
        self.ops.append(("truncate", cid, oid, size))
        return self

    def remove(self, cid: str, oid: str) -> "Transaction":
        self.ops.append(("remove", cid, oid))
        return self

    def clone(self, cid: str, src: str, dst: str) -> "Transaction":
        self.ops.append(("clone", cid, src, dst))
        return self

    def try_clone(self, cid: str, src: str, dst: str) -> "Transaction":
        """Clone if src exists, else no-op (EC rollback stashes: a
        behind shard may legitimately lack the object)."""
        self.ops.append(("try_clone", cid, src, dst))
        return self

    def try_remove(self, cid: str, oid: str) -> "Transaction":
        self.ops.append(("try_remove", cid, oid))
        return self

    def collection_move_rename(self, src_cid: str, src_oid: str,
                               dst_cid: str, dst_oid: str) -> "Transaction":
        self.ops.append(("move", src_cid, src_oid, dst_cid, dst_oid))
        return self

    # -- xattr / omap ops --------------------------------------------------

    def setattr(self, cid: str, oid: str, name: str,
                value: bytes) -> "Transaction":
        self.ops.append(("setattr", cid, oid, name, bytes(value)))
        return self

    def rmattr(self, cid: str, oid: str, name: str) -> "Transaction":
        self.ops.append(("rmattr", cid, oid, name))
        return self

    def omap_setkeys(self, cid: str, oid: str,
                     kv: dict[str, bytes]) -> "Transaction":
        self.ops.append(("omap_set", cid, oid,
                         {k: bytes(v) for k, v in kv.items()}))
        return self

    def omap_rmkeys(self, cid: str, oid: str,
                    keys: Iterable[str]) -> "Transaction":
        self.ops.append(("omap_rm", cid, oid, list(keys)))
        return self

    def omap_clear(self, cid: str, oid: str) -> "Transaction":
        self.ops.append(("omap_clear", cid, oid))
        return self

    def append(self, other: "Transaction") -> "Transaction":
        self.ops.extend(other.ops)
        self.on_applied.extend(other.on_applied)
        self.on_commit.extend(other.on_commit)
        return self

    def register_on_applied(self, cb: Callable) -> None:
        self.on_applied.append(cb)

    def register_on_commit(self, cb: Callable) -> None:
        self.on_commit.append(cb)

    @property
    def empty(self) -> bool:
        return not self.ops


class ObjectStore(abc.ABC):
    """Abstract store; all writes via queue_transactions."""

    def __init__(self):
        self._apply_lock = threading.Lock()
        # entity name of the owning daemon ("osd.3"); lets targeted
        # FaultSet store_eio rules select exactly this store
        self.owner = ""
        self.inject_eio_probability = 0.0
        # monotonically bumped on every applied transaction batch: a
        # cheap store-wide version for listing caches (backfill's
        # scan_range keeps its sorted base listing while this tick is
        # unchanged, instead of re-listing the collection per batch)
        self.mutation_tick = 0
        # crash-consistency plane: a fired crash point (or an abrupt
        # daemon abort) freezes the store — no further mutation
        # reaches disk, simulating the instant after power loss
        self.frozen = False
        self.crash_site = ""
        self.crash_callback: Callable | None = None

    def _maybe_eio(self, oid: str = "") -> None:
        """Fault hook every backend's read path consults: targeted
        FaultSet store_eio rules plus the legacy probability knob."""
        from ..utils import faults
        if faults.get().should_store_eio(self.owner, oid,
                                         self.inject_eio_probability):
            raise StoreError(EIO, f"injected EIO on {oid or '?'}")

    # -- crash plane -------------------------------------------------------

    def freeze(self) -> None:
        """Stop all disk mutation (simulated power loss / kill -9).
        Reads may keep working during teardown; every write path
        raises CrashPoint from here on."""
        self.frozen = True

    def _check_frozen(self) -> None:
        if self.frozen:
            raise CrashPoint(
                f"{self.owner or '?'}: store frozen (crashed"
                f"{' at ' + self.crash_site if self.crash_site else ''})")

    def _maybe_crash(self, site: str) -> None:
        """Named crash point: consult the FaultSet crash rules and, on
        a hit, freeze + abort (via _panic)."""
        from ..utils import faults
        if faults.get().should_crash(self.owner, site):
            self._panic(site)

    def _panic(self, site: str) -> None:
        """A crash point fired: freeze the store, notify the owning
        daemon (it aborts from a separate thread), and unwind the
        calling op without ever acking."""
        self.frozen = True
        self.crash_site = site
        cb = self.crash_callback
        if cb is not None:
            try:
                cb(site)
            except Exception:
                pass
        raise CrashPoint(f"{self.owner or '?'} crashed at {site}")

    def journal_stats(self) -> dict:
        """Recovery/journal counters (journaled backends override)."""
        return {}

    def crash_sites(self) -> list[str]:
        """The named crash points this backend threads through its
        write path (surfaced in `perf dump` crash block)."""
        return ["store.pre_apply", "store.post_apply", "pglog.append"]

    def health_warning(self) -> str | None:
        """A store-level condition worth a cluster HEALTH_WARN (e.g.
        repeated checkpoint failures); None when healthy."""
        return None

    # -- lifecycle ---------------------------------------------------------

    def mkfs(self) -> None:
        pass

    def mount(self) -> None:
        pass

    def umount(self) -> None:
        pass

    # -- write path --------------------------------------------------------

    @abc.abstractmethod
    def _do_transaction(self, txn: Transaction) -> None:
        """Apply every op or raise (partial application is a store bug)."""

    def queue_transactions(self, txns: list[Transaction],
                           on_commit: Callable | None = None) -> None:
        """Apply + schedule commit callbacks.

        Base implementation is apply-synchronous, commit-asynchronous-
        immediate; journaled backends override commit scheduling.

        Every applied transaction is reported to the EC HBM stripe
        cache's coherence scan (ops.hbm_cache.note_store_txn): a data
        mutation of a cached object's shard files invalidates its
        entry unless the txn attests the entry's exact version — the
        cache can therefore never serve bytes the store no longer
        holds, no matter which path (client write, recovery push,
        rewind, injected corruption) mutated them.
        """
        from ..ops import hbm_cache
        from ..utils import optracker
        with self._apply_lock, optracker.span("store_apply"):
            self._check_frozen()
            self._maybe_crash("store.pre_apply")
            # coherence scan BEFORE the mutation applies: a concurrent
            # scrub/recovery lookup during the apply window must miss
            # (conservative), never serve an entry whose shard files
            # are mid-rewrite.  The keep/drop decision depends only on
            # the txn's ops, so scanning early is always safe.
            for t in txns:
                hbm_cache.note_store_txn(t.ops)
            for t in txns:
                self._do_transaction(t)
            # tick bumps AFTER the apply: a concurrent listing taken
            # mid-apply carries the OLD tick and is invalidated by
            # this bump — bumping first would let a pre-apply listing
            # cache under the post-apply tick and go permanently
            # stale (a backfill scan could then miss the new object
            # forever)
            self.mutation_tick += 1
            # post-apply, pre-ack: the durability point has passed but
            # the commit callbacks (the client ack) have not fired
            self._maybe_crash("store.post_apply")
        for t in txns:
            for cb in t.on_applied:
                cb()
            for cb in t.on_commit:
                cb()
        if on_commit:
            on_commit()

    def queue_transaction(self, txn: Transaction,
                          on_commit: Callable | None = None) -> None:
        self.queue_transactions([txn], on_commit)

    def apply_transactions(self, txns: list[Transaction]) -> None:
        done = threading.Event()
        self.queue_transactions(txns, on_commit=done.set)
        done.wait()

    def apply_transaction(self, txn: Transaction) -> None:
        self.apply_transactions([txn])

    # -- read path ---------------------------------------------------------

    @abc.abstractmethod
    def read(self, cid: str, oid: str, offset: int = 0,
             length: int = 0) -> bytes:
        """length == 0 -> to EOF.  Raises StoreError(ENOENT)."""

    @abc.abstractmethod
    def stat(self, cid: str, oid: str) -> dict: ...

    @abc.abstractmethod
    def exists(self, cid: str, oid: str) -> bool: ...

    @abc.abstractmethod
    def getattr(self, cid: str, oid: str, name: str) -> bytes: ...

    @abc.abstractmethod
    def getattrs(self, cid: str, oid: str) -> dict[str, bytes]: ...

    @abc.abstractmethod
    def omap_get(self, cid: str, oid: str) -> dict[str, bytes]: ...

    @abc.abstractmethod
    def omap_get_values(self, cid: str, oid: str,
                        keys: Iterable[str]) -> dict[str, bytes]: ...

    def omap_get_vals(self, cid: str, oid: str, start_after: str = "",
                      prefix: str = "",
                      max_return: int = 0) -> dict[str, bytes]:
        """Ordered slice of an omap (ObjectStore omap_get_vals
        semantics): keys strictly after `start_after`, filtered by
        `prefix`, at most `max_return` (0 = unlimited).  Backends
        with sorted storage may override; this default slices the
        full map."""
        omap = self.omap_get(cid, oid)
        out: dict[str, bytes] = {}
        for k in sorted(omap):
            if start_after and k <= start_after:
                continue
            if prefix and not k.startswith(prefix):
                continue
            out[k] = omap[k]
            if max_return and len(out) >= max_return:
                break
        return out

    @abc.abstractmethod
    def list_collections(self) -> list[str]: ...

    @abc.abstractmethod
    def collection_exists(self, cid: str) -> bool: ...

    @abc.abstractmethod
    def collection_list(self, cid: str, start: str = "",
                        max_count: int = 0) -> list[str]:
        """Sorted object names > start (the backfill/scrub scan order)."""
