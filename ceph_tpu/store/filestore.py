"""JournalFileStore: write-ahead journal + MemStore state + disk image.

The FileStore analog (os/filestore/FileStore.cc:2048 semantics):
queue_transactions appends the serialized transaction batch to a
write-ahead journal (fsync'd), applies to the in-memory state, and acks
commit once journaled — a crash replays the journal over the last
snapshot on mount (FileJournal + "journal writeahead" mode).  A
background committer periodically snapshots state to disk and trims the
journal (the "sync/commit interval").

Data layout under `path/`:
  journal      append-only record stream; each record is
               <u64 len><u64 seq><u32 crc32c(payload)><payload>
               (FileJournal entry_header_t reduced: the crc makes a
               bit-flipped or bad-length record detectable, the seq
               makes a reordered/resurrected one detectable)
  snapshot     CSN2 <u32 crc32c(body)> <compressed denc state>; the
               state records the journal offset AND the next record
               seq it covers

Recovery contract (the ALICE torn-write findings, OSDI '14, applied):
replay stops cleanly at the first torn or corrupt record, discards the
tail ON DISK (truncate to the last valid record, so later appends
extend a parseable journal), and counts what it dropped
(journal_torn_tail_discards / journal_bad_record_halts).  A corrupt or
truncated snapshot — bad magic, bad crc, failed decompress — falls
back to full-journal replay with a counter and a warning, never a
crash and never silently.

Crash points (FaultSet `crash <prob> <site>` rules, seed-
deterministic): journal.pre_fsync (record written but not fsync'd —
an arbitrary seeded prefix survives, the torn-write model),
journal.post_fsync (durable but unacked), journal.mid_apply,
snapshot.mid_write (torn tmp file), snapshot.pre_rename (complete tmp,
old snapshot still live).  A fired point freezes the store and aborts
the owning daemon without acking.
"""

from __future__ import annotations

import os
import struct
import threading
from typing import Callable

from ..ops.crc32c import crc32c
from ..utils import copyaudit, denc
from ..utils.dout import DoutLogger
from ..utils.faults import CrashPoint
from .memstore import MemStore
from .objectstore import StoreError, Transaction

_REC = struct.Struct("<QQI")     # record header: len, seq, payload crc
_SNAP_CRC = struct.Struct("<I")
MAGIC = b"CTJ2"
SNAP_MAGIC = b"CSN2"

# consecutive checkpoint failures before the daemon surfaces a
# HEALTH_WARN (the committer keeps retrying regardless)
CHECKPOINT_WARN_AFTER = 3


class JournalFileStore(MemStore):
    compression = "zlib"     # snapshot codec (compressor registry)

    def __init__(self, path: str, commit_interval: float = 0.2):
        super().__init__()
        self.path = path
        self.commit_interval = commit_interval
        self._journal_path = os.path.join(path, "journal")
        self._snap_path = os.path.join(path, "snapshot")
        self._jf = None
        self._jlock = threading.Lock()
        self._committer: threading.Thread | None = None
        self._stop = threading.Event()
        # a valid journal is never shorter than its magic; an umount
        # before any mount (mkfs-only stores) checkpoints this value,
        # so it must never point a snapshot at offset 0
        self._journal_len = len(MAGIC)
        self._next_seq = 1
        self._ckpt_fails = 0          # consecutive
        self.log = DoutLogger("filestore", path or "?")
        self.counters = {
            "journal_records_replayed": 0,
            "journal_torn_tail_discards": 0,
            "journal_bad_record_halts": 0,
            "journal_tail_bytes_discarded": 0,
            "snapshot_corrupt_fallbacks": 0,
            "journal_checkpoint_errors": 0,
            "journal_checkpoints": 0,
            "fsync_reorder_windows": 0,
        }

    def journal_stats(self) -> dict:
        return dict(self.counters)

    def crash_sites(self) -> list[str]:
        return ["journal.pre_fsync", "journal.post_fsync",
                "journal.mid_apply", "snapshot.mid_write",
                "snapshot.pre_rename", "pglog.append"]

    def health_warning(self) -> str | None:
        n = self._ckpt_fails
        if n >= CHECKPOINT_WARN_AFTER:
            return f"{n} consecutive journal checkpoint failures"
        return None

    # -- lifecycle ---------------------------------------------------------

    def mkfs(self) -> None:
        os.makedirs(self.path, exist_ok=True)
        with open(self._journal_path, "wb") as f:
            f.write(MAGIC)
        self._write_snapshot(len(MAGIC), 1)

    def mount(self) -> None:
        if not os.path.exists(self._journal_path):
            raise FileNotFoundError(f"{self.path} not mkfs'd")
        self.log = DoutLogger("filestore", self.owner or self.path)
        # a stray snapshot.tmp is a checkpoint interrupted mid-write
        # or pre-rename: never read, never trusted — drop it
        try:
            os.unlink(self._snap_path + ".tmp")
        except OSError:
            pass
        self._replay()
        self._jf = open(self._journal_path, "ab")
        self._journal_len = self._jf.tell()
        self._stop.clear()
        self._committer = threading.Thread(target=self._commit_loop,
                                           daemon=True)
        self._committer.start()

    def umount(self) -> None:
        self._stop.set()
        if self._committer:
            self._committer.join(timeout=5)
            self._committer = None
        if not self.frozen:
            try:
                self._checkpoint()
            except CrashPoint:
                pass
        with self._jlock:
            if self._jf:
                self._jf.close()
                self._jf = None

    # -- journaling --------------------------------------------------------

    def queue_transactions(self, txns: list[Transaction],
                           on_commit: Callable | None = None) -> None:
        self._check_frozen()
        # THE write-path flatten: shard views/ropes serialize into one
        # contiguous WAL record here — by design the only place the
        # data path materializes payload bytes (audited)
        batch = denc.dumps([t.ops for t in txns])
        copyaudit.note("journal.append", len(batch))
        from ..ops import hbm_cache
        from ..utils import optracker
        with self._jlock:
            self._check_frozen()
            # traced: the journal span covers lock-held append+fsync
            # (the durability cost a client write pays here); a crash
            # point unwinding through it leaves the span open — the
            # flight recorder then shows the op dead mid-journal
            with optracker.span("journal", bytes=len(batch)):
                # the seq is claimed INSIDE the lock: two racing
                # writers stamping the same seq would read as
                # corruption on replay (wrong-seq halt) and truncate
                # the tail — every acked write behind it would vanish
                record = _REC.pack(len(batch), self._next_seq,
                                   crc32c(0, batch)) + batch
                self._jf.write(record)
                self._jf.flush()
                # crash site: bytes handed to the OS but not fsync'd —
                # a power loss keeps an arbitrary (seeded) prefix
                self._crash_torn_tail("journal.pre_fsync", len(record))
                os.fsync(self._jf.fileno())
                self._next_seq += 1
                self._journal_len = self._jf.tell()
                # crash site: record durable, ack not yet sent
                self._maybe_crash("journal.post_fsync")
            # apply NESTED inside the journal lock: the committer's
            # snapshot cut (_jlock + _apply_lock) must never observe
            # a journal offset past a record whose effects are not in
            # _colls yet — a crash after such a checkpoint replays
            # from past the record and silently drops an acked write.
            # Nesting also pins apply order to journal order, the
            # invariant replay reconstructs state by.  (HBM stripe
            # cache coherence scan runs before the apply; see
            # ObjectStore.queue_transactions for that rationale.)
            with self._apply_lock, optracker.span("store_apply"):
                self._check_frozen()
                for t in txns:
                    hbm_cache.note_store_txn(t.ops)
                for i, t in enumerate(txns):
                    self._do_transaction(t)
                    if i == 0:
                        # crash site: journaled, partially applied to
                        # the (volatile) state, never acked — replay
                        # restores
                        self._maybe_crash("journal.mid_apply")
                # post-apply bump (see ObjectStore.queue_transactions:
                # a pre-apply listing must never cache under the
                # post-apply tick)
                self.mutation_tick += 1
        # journaled == durable: ack applied+committed now
        for t in txns:
            for cb in t.on_applied:
                cb()
            for cb in t.on_commit:
                cb()
        if on_commit:
            on_commit()

    def _crash_torn_tail(self, site: str, rec_len: int) -> None:
        """Roll the crash rules for a torn-write site; on a hit keep a
        seeded prefix of the un-fsync'd record and panic.  With an
        fsync_reorder rule armed, the record's 4 KiB pages instead
        persist as a seeded SUBSET — sectors of one un-fsync'd write
        can land out of order (ALICE's reordering window), so a LATER
        page may be durable while an earlier one reads back as zeros.
        Replay must still honor the prefix promise: it halts at the
        first damaged page (crc/seq) and discards everything after,
        including pages that physically survived."""
        from ..utils import faults
        fs = faults.get()
        if not fs.should_crash(self.owner, site):
            return
        if fs.reorder_armed(self.owner):
            page = 4096
            npages = (rec_len + page - 1) // page
            mask = fs.torn_survivors(self.owner, npages)
            self._jf.flush()
            with open(self._journal_path, "r+b") as f:
                for i, keep in enumerate(mask):
                    if keep:
                        continue
                    start = self._journal_len + i * page
                    end = min(self._journal_len + rec_len, start + page)
                    f.seek(start)
                    f.write(b"\x00" * (end - start))
                f.flush()
                os.fsync(f.fileno())
            self.counters["fsync_reorder_windows"] += 1
        else:
            keep = int(fs.torn_keep_fraction(self.owner) * rec_len)
            self._jf.truncate(self._journal_len + keep)
            self._jf.flush()
            os.fsync(self._jf.fileno())
        self._panic(site)

    # -- recovery ----------------------------------------------------------

    def _load_snapshot(self) -> dict | None:
        """Parse + verify the snapshot; None -> full-journal replay
        (absent on a fresh mkfs is normal; corrupt counts + warns)."""
        if not os.path.exists(self._snap_path):
            return None

        def corrupt(why: str) -> None:
            self.counters["snapshot_corrupt_fallbacks"] += 1
            self.log.warn("snapshot %s %s: falling back to full-journal "
                          "replay", self._snap_path, why)

        with open(self._snap_path, "rb") as f:
            raw = f.read()
        if not raw.startswith(SNAP_MAGIC) or \
                len(raw) < len(SNAP_MAGIC) + _SNAP_CRC.size:
            corrupt("has bad magic")
            return None
        (want_crc,) = _SNAP_CRC.unpack_from(raw, len(SNAP_MAGIC))
        body = raw[len(SNAP_MAGIC) + _SNAP_CRC.size:]
        if crc32c(0, body) != want_crc:
            corrupt("failed its crc")
            return None
        try:
            from ..compressor import decompress_any
            snap = denc.loads(decompress_any(body))
            snap["journal_offset"] = int(snap["journal_offset"])
            snap["journal_seq"] = int(snap.get("journal_seq", 1))
            snap["colls"]
        except Exception as e:
            corrupt(f"failed to decode ({type(e).__name__})")
            return None
        return snap

    def _replay(self) -> None:
        """Load snapshot (or fall back), then re-apply journal records
        past it, halting cleanly at the first torn/corrupt record and
        discarding the unparseable tail on disk."""
        start = len(MAGIC)
        next_seq = 1
        snap = self._load_snapshot()
        self._colls.clear()
        if snap is not None:
            # never below the magic: a snapshot pointing into (or at)
            # the header would make replay parse the magic bytes as a
            # record and truncate them away as an unparseable tail
            start = max(snap["journal_offset"], len(MAGIC))
            next_seq = snap["journal_seq"]
            from .memstore import _Obj
            for cid, objs in snap["colls"].items():
                coll = self._colls[cid] = {}
                for oid, (data, xattrs, omap) in objs.items():
                    o = _Obj()
                    o.data = bytearray(data)
                    o.xattrs = dict(xattrs)
                    o.omap = dict(omap)
                    coll[oid] = o
        with open(self._journal_path, "rb") as f:
            head = f.read(len(MAGIC))
            if head != MAGIC:
                raise IOError(f"bad journal magic in {self._journal_path}")
            f.seek(0, os.SEEK_END)
            journal_end = f.tell()
            f.seek(start)
            good_end = start
            while True:
                hdr = f.read(_REC.size)
                if not hdr:
                    break                      # clean end
                if len(hdr) < _REC.size:
                    self.counters["journal_torn_tail_discards"] += 1
                    break                      # torn header
                blen, seq, want_crc = _REC.unpack(hdr)
                if blen > journal_end - f.tell():
                    # promises more bytes than the file holds: a torn
                    # write OR a corrupted length — either way the
                    # tail is unusable past this point
                    self.counters["journal_torn_tail_discards"] += 1
                    break
                blob = f.read(blen)
                if crc32c(0, blob) != want_crc:
                    self.counters["journal_bad_record_halts"] += 1
                    self.log.warn("journal record seq=%d at %d failed "
                                  "its crc; discarding the tail",
                                  seq, good_end)
                    break
                if seq != next_seq:
                    self.counters["journal_bad_record_halts"] += 1
                    self.log.warn("journal record at %d has seq %d, "
                                  "expected %d; discarding the tail",
                                  good_end, seq, next_seq)
                    break
                for ops in denc.loads(blob):
                    t = Transaction()
                    t.ops = ops
                    try:
                        self._do_transaction(t)
                    except StoreError:
                        # the journal is a WAL: a txn that failed at
                        # LIVE apply time (e.g. a client remove of a
                        # never-created object NACKed with ENOENT)
                        # was still journaled first.  Replay must end
                        # in the same state the live run did — applied
                        # up to the failing op, rest of this record's
                        # batch abandoned — not refuse to mount.
                        break
                self.counters["journal_records_replayed"] += 1
                next_seq = seq + 1
                good_end = f.tell()
        if good_end < journal_end:
            # discard the unparseable tail ON DISK: a later append
            # must extend a valid record stream, not bury garbage
            # mid-journal where the next replay would halt again
            self.counters["journal_tail_bytes_discarded"] += \
                journal_end - good_end
            self.log.warn("discarding %d unparseable journal tail "
                          "bytes past offset %d",
                          journal_end - good_end, good_end)
            os.truncate(self._journal_path, good_end)
        self._next_seq = next_seq

    # -- committer ---------------------------------------------------------

    def _write_snapshot(self, journal_offset: int,
                        journal_seq: int) -> None:
        self._check_frozen()
        state = {
            "journal_offset": journal_offset,
            "journal_seq": journal_seq,
            "colls": {
                cid: {oid: (bytes(o.data), o.xattrs, o.omap)
                      for oid, o in objs.items()}
                for cid, objs in self._colls.items()
            },
        }
        # snapshots are large whole-file blobs: compression cuts the
        # checkpoint's disk footprint and fsync time (the BlueStore
        # blob-compression analog at this store's granularity)
        from ..compressor import create as compressor_create
        body = compressor_create(
            self.compression).compress(denc.dumps(state))
        blob = SNAP_MAGIC + _SNAP_CRC.pack(crc32c(0, body)) + body
        tmp = self._snap_path + ".tmp"
        from ..utils import faults
        fs = faults.get()
        with open(tmp, "wb") as f:
            if fs.should_crash(self.owner, "snapshot.mid_write"):
                if fs.reorder_armed(self.owner):
                    # fsync-reorder window on the CHECKPOINT itself:
                    # the un-fsync'd snapshot pages land as a seeded
                    # SUBSET while the rename metadata commits first —
                    # mount finds a renamed-in snapshot whose body
                    # fails its crc and MUST fall back to full-journal
                    # replay (counted), never trust the torn state
                    page = 4096
                    npages = (len(blob) + page - 1) // page
                    mask = fs.torn_survivors(self.owner, npages)
                    torn = bytearray(blob)
                    for i, keep in enumerate(mask):
                        if not keep:
                            torn[i * page:(i + 1) * page] = \
                                b"\x00" * (min(len(blob),
                                               (i + 1) * page)
                                           - i * page)
                    f.write(torn)      # bytearray: no flatten copy
                    f.flush()
                    os.fsync(f.fileno())
                    f.close()
                    os.replace(tmp, self._snap_path)
                    self.counters["fsync_reorder_windows"] += 1
                    self._panic("snapshot.mid_write")
                # torn tmp: a seeded prefix lands, the rename never
                # happens — the previous snapshot stays authoritative
                keep = int(fs.torn_keep_fraction(self.owner) * len(blob))
                f.write(blob[:keep])
                f.flush()
                os.fsync(f.fileno())
                self._panic("snapshot.mid_write")
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        # crash site: tmp complete+durable but not yet renamed in —
        # mount still reads the OLD snapshot + the full journal
        self._maybe_crash("snapshot.pre_rename")
        os.replace(tmp, self._snap_path)

    def _checkpoint(self) -> None:
        with self._jlock, self._apply_lock, self._lock:
            self._check_frozen()
            self._write_snapshot(self._journal_len, self._next_seq)
            self.counters["journal_checkpoints"] += 1

    def _commit_loop(self) -> None:
        while not self._stop.wait(self.commit_interval):
            try:
                self._checkpoint()
                self._ckpt_fails = 0
            except CrashPoint:
                return         # simulated power loss: die with the store
            except Exception as e:
                # never swallow silently: count, log, and keep the
                # consecutive-failure tally the daemon turns into a
                # HEALTH_WARN after CHECKPOINT_WARN_AFTER in a row
                self.counters["journal_checkpoint_errors"] += 1
                self._ckpt_fails += 1
                self.log.warn("journal checkpoint failed "
                              "(%d consecutive): %s: %s",
                              self._ckpt_fails, type(e).__name__, e)
