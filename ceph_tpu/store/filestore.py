"""JournalFileStore: write-ahead journal + MemStore state + disk image.

The FileStore analog (os/filestore/FileStore.cc:2048 semantics):
queue_transactions appends the serialized transaction batch to a
write-ahead journal (fsync'd), applies to the in-memory state, and acks
commit once journaled — a crash replays the journal over the last
snapshot on mount (FileJournal + "journal writeahead" mode).  A
background committer periodically snapshots state to disk and trims the
journal (the "sync/commit interval").

Data layout under `path/`:
  journal      append-only length-prefixed denc op batches
  snapshot     denc full state + the journal offset it covers
"""

from __future__ import annotations

import os
import struct
import threading
import time
from typing import Callable

from ..utils import denc
from .memstore import MemStore
from .objectstore import Transaction

_LEN = struct.Struct("<Q")
MAGIC = b"CTJ1"
SNAP_MAGIC = b"CSNP"


class JournalFileStore(MemStore):
    compression = "zlib"     # snapshot codec (compressor registry)

    def __init__(self, path: str, commit_interval: float = 0.2):
        super().__init__()
        self.path = path
        self.commit_interval = commit_interval
        self._journal_path = os.path.join(path, "journal")
        self._snap_path = os.path.join(path, "snapshot")
        self._jf = None
        self._jlock = threading.Lock()
        self._committer: threading.Thread | None = None
        self._stop = threading.Event()
        self._journal_len = 0

    # -- lifecycle ---------------------------------------------------------

    def mkfs(self) -> None:
        os.makedirs(self.path, exist_ok=True)
        with open(self._journal_path, "wb") as f:
            f.write(MAGIC)
        self._write_snapshot(len(MAGIC))

    def mount(self) -> None:
        if not os.path.exists(self._journal_path):
            raise FileNotFoundError(f"{self.path} not mkfs'd")
        self._replay()
        self._jf = open(self._journal_path, "ab")
        self._journal_len = self._jf.tell()
        self._stop.clear()
        self._committer = threading.Thread(target=self._commit_loop,
                                           daemon=True)
        self._committer.start()

    def umount(self) -> None:
        self._stop.set()
        if self._committer:
            self._committer.join(timeout=5)
            self._committer = None
        self._checkpoint()
        if self._jf:
            self._jf.close()
            self._jf = None

    # -- journaling --------------------------------------------------------

    def queue_transactions(self, txns: list[Transaction],
                           on_commit: Callable | None = None) -> None:
        batch = denc.dumps([t.ops for t in txns])
        with self._jlock:
            self._jf.write(_LEN.pack(len(batch)))
            self._jf.write(batch)
            self._jf.flush()
            os.fsync(self._jf.fileno())
            self._journal_len = self._jf.tell()
        # HBM stripe cache coherence scan before the apply (see
        # ObjectStore.queue_transactions for the ordering rationale)
        from ..ops import hbm_cache
        with self._apply_lock:
            for t in txns:
                hbm_cache.note_store_txn(t.ops)
            for t in txns:
                self._do_transaction(t)
        # journaled == durable: ack applied+committed now
        for t in txns:
            for cb in t.on_applied:
                cb()
            for cb in t.on_commit:
                cb()
        if on_commit:
            on_commit()

    def _replay(self) -> None:
        """Load snapshot, then re-apply journal entries past it."""
        start = len(MAGIC)
        if os.path.exists(self._snap_path):
            with open(self._snap_path, "rb") as f:
                raw = f.read()
            if raw.startswith(SNAP_MAGIC):
                from ..compressor import decompress_any
                raw = decompress_any(raw[len(SNAP_MAGIC):])
            snap = denc.loads(raw)
            start = snap["journal_offset"]
            self._colls.clear()
            from .memstore import _Obj
            for cid, objs in snap["colls"].items():
                coll = self._colls[cid] = {}
                for oid, (data, xattrs, omap) in objs.items():
                    o = _Obj()
                    o.data = bytearray(data)
                    o.xattrs = dict(xattrs)
                    o.omap = dict(omap)
                    coll[oid] = o
        with open(self._journal_path, "rb") as f:
            head = f.read(len(MAGIC))
            if head != MAGIC:
                raise IOError(f"bad journal magic in {self._journal_path}")
            f.seek(start)
            while True:
                hdr = f.read(_LEN.size)
                if len(hdr) < _LEN.size:
                    break
                (blen,) = _LEN.unpack(hdr)
                blob = f.read(blen)
                if len(blob) < blen:
                    break  # torn tail write: discard (pre-commit crash)
                for ops in denc.loads(blob):
                    t = Transaction()
                    t.ops = ops
                    self._do_transaction(t)

    # -- committer ---------------------------------------------------------

    def _write_snapshot(self, journal_offset: int) -> None:
        state = {
            "journal_offset": journal_offset,
            "colls": {
                cid: {oid: (bytes(o.data), o.xattrs, o.omap)
                      for oid, o in objs.items()}
                for cid, objs in self._colls.items()
            },
        }
        # snapshots are large whole-file blobs: compression cuts the
        # checkpoint's disk footprint and fsync time (the BlueStore
        # blob-compression analog at this store's granularity)
        from ..compressor import create as compressor_create
        blob = SNAP_MAGIC + compressor_create(
            self.compression).compress(denc.dumps(state))
        tmp = self._snap_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._snap_path)

    def _checkpoint(self) -> None:
        with self._jlock, self._apply_lock, self._lock:
            self._write_snapshot(self._journal_len)

    def _commit_loop(self) -> None:
        while not self._stop.wait(self.commit_interval):
            try:
                self._checkpoint()
            except Exception:
                import traceback
                traceback.print_exc()
