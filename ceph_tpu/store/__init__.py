"""ObjectStore: transactional local object storage (os/ analog).

Objects (data + xattrs + omap) live in collections; all mutations go
through Transactions applied atomically via queue_transactions with
async commit callbacks (os/ObjectStore.h:1453 semantics).  Backends:
MemStore (in-RAM, tests/fast OSDs) and JournalFileStore (write-ahead
journal + files + sqlite omap, the FileStore analog).
"""

from .objectstore import (ObjectStore, Transaction, StoreError, CrashPoint,
                          ENOENT, EEXIST)
from .memstore import MemStore
from .filestore import JournalFileStore


def create(kind: str, path: str = "", **kw) -> ObjectStore:
    """ObjectStore::create factory (os/ObjectStore.h:83)."""
    if kind == "memstore":
        return MemStore()
    if kind in ("filestore", "journalfilestore"):
        return JournalFileStore(path, **kw)
    if kind == "kstore":
        from .kstore import KStore
        return KStore(path)
    if kind == "blockstore":
        from .blockstore import BlockStore
        return BlockStore(path, **kw)
    raise ValueError(f"unknown objectstore {kind!r}")


__all__ = ["ObjectStore", "Transaction", "StoreError", "CrashPoint",
           "MemStore", "JournalFileStore", "create", "ENOENT", "EEXIST"]
