"""MemStore: all-in-RAM ObjectStore (os/memstore/MemStore.h:32 analog).

The fast backend for tests and single-process clusters; also the model
every other backend's conformance is checked against.
"""

from __future__ import annotations

import threading
from typing import Iterable

from .objectstore import (EEXIST, ENOENT, ObjectStore, StoreError,
                          Transaction)


class _Obj:
    __slots__ = ("data", "xattrs", "omap")

    def __init__(self):
        self.data = bytearray()
        self.xattrs: dict[str, bytes] = {}
        self.omap: dict[str, bytes] = {}

    def clone(self) -> "_Obj":
        o = _Obj()
        o.data = bytearray(self.data)
        o.xattrs = dict(self.xattrs)
        o.omap = dict(self.omap)
        return o


class MemStore(ObjectStore):
    def __init__(self, inject_eio_probability: float = 0.0):
        super().__init__()
        self._colls: dict[str, dict[str, _Obj]] = {}
        self._lock = threading.RLock()
        self.inject_eio_probability = inject_eio_probability

    # -- transaction application ------------------------------------------

    def _get(self, cid: str, oid: str, create: bool = False) -> _Obj:
        coll = self._colls.get(cid)
        if coll is None:
            raise StoreError(ENOENT, f"no collection {cid}")
        obj = coll.get(oid)
        if obj is None:
            if not create:
                raise StoreError(ENOENT, f"no object {cid}/{oid}")
            obj = coll[oid] = _Obj()
        return obj

    def _do_transaction(self, txn: Transaction) -> None:
        with self._lock:
            for op in txn.ops:
                self._do_op(op)

    def _do_op(self, op: tuple) -> None:
        kind = op[0]
        if kind == "mkcoll":
            if op[1] in self._colls:
                raise StoreError(EEXIST, f"collection {op[1]} exists")
            self._colls[op[1]] = {}
        elif kind == "rmcoll":
            self._colls.pop(op[1], None)
        elif kind == "touch":
            self._get(op[1], op[2], create=True)
        elif kind == "write":
            _, cid, oid, offset, data = op
            obj = self._get(cid, oid, create=True)
            end = offset + len(data)
            if len(obj.data) < end:
                obj.data.extend(b"\x00" * (end - len(obj.data)))
            obj.data[offset:end] = data
        elif kind == "zero":
            _, cid, oid, offset, length = op
            obj = self._get(cid, oid, create=True)
            end = offset + length
            if len(obj.data) < end:
                obj.data.extend(b"\x00" * (end - len(obj.data)))
            obj.data[offset:end] = b"\x00" * length
        elif kind == "truncate":
            _, cid, oid, size = op
            obj = self._get(cid, oid, create=True)
            if len(obj.data) > size:
                del obj.data[size:]
            else:
                obj.data.extend(b"\x00" * (size - len(obj.data)))
        elif kind == "remove":
            coll = self._colls.get(op[1])
            if coll is None or op[2] not in coll:
                raise StoreError(ENOENT, f"remove {op[1]}/{op[2]}")
            del coll[op[2]]
        elif kind == "try_remove":
            coll = self._colls.get(op[1])
            if coll is not None:
                coll.pop(op[2], None)
        elif kind == "clone":
            _, cid, src, dst = op
            obj = self._get(cid, src)
            self._colls[cid][dst] = obj.clone()
        elif kind == "try_clone":
            _, cid, src, dst = op
            coll = self._colls.get(cid)
            if coll is not None and src in coll:
                coll[dst] = coll[src].clone()
        elif kind == "move":
            _, scid, soid, dcid, doid = op
            obj = self._get(scid, soid)
            if dcid not in self._colls:
                raise StoreError(ENOENT, f"no collection {dcid}")
            self._colls[dcid][doid] = obj
            del self._colls[scid][soid]
        elif kind == "setattr":
            _, cid, oid, name, value = op
            self._get(cid, oid, create=True).xattrs[name] = value
        elif kind == "rmattr":
            self._get(op[1], op[2]).xattrs.pop(op[3], None)
        elif kind == "omap_set":
            self._get(op[1], op[2], create=True).omap.update(op[3])
        elif kind == "omap_rm":
            omap = self._get(op[1], op[2]).omap
            for k in op[3]:
                omap.pop(k, None)
        elif kind == "omap_clear":
            self._get(op[1], op[2]).omap.clear()
        else:
            raise StoreError(EEXIST, f"unknown op {kind}")

    # -- reads -------------------------------------------------------------

    def read(self, cid: str, oid: str, offset: int = 0,
             length: int = 0) -> bytes:
        self._maybe_eio(oid)
        with self._lock:
            obj = self._get(cid, oid)
            if length == 0:
                return bytes(obj.data[offset:])
            return bytes(obj.data[offset:offset + length])

    def stat(self, cid: str, oid: str) -> dict:
        with self._lock:
            obj = self._get(cid, oid)
            return {"size": len(obj.data)}

    def exists(self, cid: str, oid: str) -> bool:
        with self._lock:
            return oid in self._colls.get(cid, {})

    def getattr(self, cid: str, oid: str, name: str) -> bytes:
        with self._lock:
            obj = self._get(cid, oid)
            if name not in obj.xattrs:
                raise StoreError(ENOENT, f"no xattr {name}")
            return obj.xattrs[name]

    def getattrs(self, cid: str, oid: str) -> dict[str, bytes]:
        with self._lock:
            return dict(self._get(cid, oid).xattrs)

    def omap_get(self, cid: str, oid: str) -> dict[str, bytes]:
        with self._lock:
            return dict(self._get(cid, oid).omap)

    def omap_get_values(self, cid: str, oid: str,
                        keys: Iterable[str]) -> dict[str, bytes]:
        with self._lock:
            omap = self._get(cid, oid).omap
            return {k: omap[k] for k in keys if k in omap}

    def list_collections(self) -> list[str]:
        with self._lock:
            return sorted(self._colls)

    def collection_exists(self, cid: str) -> bool:
        with self._lock:
            return cid in self._colls

    def collection_list(self, cid: str, start: str = "",
                        max_count: int = 0) -> list[str]:
        with self._lock:
            if cid not in self._colls:
                raise StoreError(ENOENT, f"no collection {cid}")
            names = sorted(n for n in self._colls[cid] if n > start)
        return names[:max_count] if max_count else names
