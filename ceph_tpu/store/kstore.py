"""KStore: the all-in-KV object store (os/kstore/KStore.cc analog; the
BlueStore-family "metadata and data both live in the KV tier" model).

Layout in the KeyValueDB, one prefix per kind (the reference's
PREFIX_SUPER/COLL/OBJ/DATA/OMAP discipline):

  C  <cid>                      -> b"1"            collection exists
  O  <cid>/<oid>                -> denc {size, xattrs}   object head
  D  <cid>/<oid>/<block#:016x>  -> raw bytes       data, fixed blocks
  M  <cid>/<oid>/<key>          -> raw bytes       omap

Data is chunked into fixed blocks so partial writes touch only the
blocks they cover — the extent-blob model at its simplest.  Every
ObjectStore Transaction becomes ONE KV transaction, so the atomicity
contract is the KV engine's (Sqlite journal on disk, dict swap in
memory); there is no separate WAL because the KV commit IS the
durability point (BlueStore's kv_sync_thread collapsed).
"""

from __future__ import annotations

import threading

from ..kv.keyvaluedb import KeyValueDB
from ..kv.memdb import MemDB
from ..kv.sqlitedb import SqliteDB
from ..utils import denc
from .objectstore import (EEXIST, ENOENT, ObjectStore, StoreError,
                          Transaction)

BLOCK = 64 * 1024

P_COLL = "C"
P_OBJ = "O"
P_DATA = "D"
P_OMAP = "M"


def _okey(cid: str, oid: str) -> str:
    return f"{cid}/{oid}"


def _dkey(cid: str, oid: str, block: int) -> str:
    return f"{cid}/{oid}/{block:016x}"


class KStore(ObjectStore):
    def __init__(self, path: str = ""):
        super().__init__()
        self.path = path
        self.db: KeyValueDB = SqliteDB(f"{path}/kstore.db") if path \
            else MemDB()
        self._lock = threading.RLock()

    # -- lifecycle ---------------------------------------------------------

    def mkfs(self) -> None:
        if self.path:
            import os
            os.makedirs(self.path, exist_ok=True)
            self.db = SqliteDB(f"{self.path}/kstore.db")
        self.db.open()

    def mount(self) -> None:
        if self.path:
            import os
            if not os.path.exists(f"{self.path}/kstore.db"):
                raise FileNotFoundError(f"{self.path}/kstore.db")
        self.db.open()

    def umount(self) -> None:
        self.db.close()

    # -- head helpers ------------------------------------------------------

    def _head(self, cid: str, oid: str) -> dict:
        blob = self.db.get(P_OBJ, _okey(cid, oid))
        if blob is None:
            raise StoreError(ENOENT, f"no object {cid}/{oid}")
        return denc.loads(blob)

    def _head_or_new(self, st: dict, cid: str, oid: str,
                     create: bool) -> dict:
        heads = st["heads"]
        key = _okey(cid, oid)
        if key in heads:
            head = heads[key]
            if head is None:
                if not create:
                    raise StoreError(ENOENT, f"no object {cid}/{oid}")
                head = heads[key] = {"size": 0, "xattrs": {}}
            return head
        blob = self.db.get(P_OBJ, key)
        if blob is None:
            if not create:
                raise StoreError(ENOENT, f"no object {cid}/{oid}")
            if cid not in st["new_colls"] and \
                    self.db.get(P_COLL, cid) is None:
                raise StoreError(ENOENT, f"no collection {cid}")
            head = {"size": 0, "xattrs": {}}
        else:
            head = denc.loads(blob)
        heads[key] = head
        return head

    # -- data block rmw ----------------------------------------------------

    def _read_block(self, datas: dict, cid: str, oid: str,
                    block: int) -> bytes:
        key = _dkey(cid, oid, block)
        if key in datas:
            return datas[key] or b""
        return self.db.get(P_DATA, key) or b""

    def _write_span(self, datas: dict, cid: str, oid: str, offset: int,
                    data: bytes) -> None:
        pos = 0
        while pos < len(data):
            block = (offset + pos) // BLOCK
            boff = (offset + pos) % BLOCK
            take = min(len(data) - pos, BLOCK - boff)
            cur = bytearray(self._read_block(datas, cid, oid, block))
            if len(cur) < boff + take:
                cur.extend(b"\x00" * (boff + take - len(cur)))
            cur[boff: boff + take] = data[pos: pos + take]
            datas[_dkey(cid, oid, block)] = bytes(cur)
            pos += take

    # -- transaction application ------------------------------------------

    def _do_transaction(self, txn: Transaction) -> None:
        with self._lock:
            self._check_frozen()     # crashed: nothing reaches the KV
            kvt = self.db.transaction()
            st = {"heads": {}, "new_colls": set(), "omaps": {}}
            datas: dict[str, bytes | None] = {}   # pending data blocks
            for op in txn.ops:
                self._apply_op(op, st, datas, kvt)
            for key, head in st["heads"].items():
                if head is None:
                    kvt.rmkey(P_OBJ, key)
                else:
                    kvt.set(P_OBJ, key, denc.dumps(head))
            for key, blob in datas.items():
                if blob is None:
                    kvt.rmkey(P_DATA, key)
                else:
                    kvt.set(P_DATA, key, blob)
            for key, val in st["omaps"].items():
                if val is None:
                    kvt.rmkey(P_OMAP, key)
                else:
                    kvt.set(P_OMAP, key, val)
            self.db.submit_transaction(kvt, sync=True)

    def _omap_items(self, st: dict, cid: str, oid: str):
        """Committed omap entries overlaid with this txn's staged
        writes — later ops (remove/clone) must see earlier ones."""
        prefix = f"{cid}/{oid}/"
        out = {}
        for key, val in self.db.iterate(P_OMAP, prefix):
            if not key.startswith(prefix):
                break
            out[key[len(prefix):]] = val
        for key, val in st["omaps"].items():
            if key.startswith(prefix):
                k = key[len(prefix):]
                if val is None:
                    out.pop(k, None)
                else:
                    out[k] = val
        return out

    def _apply_op(self, op, st, datas, kvt) -> None:
        heads = st["heads"]
        kind = op[0]
        if kind == "mkcoll":
            _, cid = op
            if self.db.get(P_COLL, cid) is not None:
                raise StoreError(EEXIST, f"collection {cid} exists")
            st["new_colls"].add(cid)
            kvt.set(P_COLL, cid, b"1")
        elif kind == "rmcoll":
            _, cid = op
            kvt.rmkey(P_COLL, cid)
            st["new_colls"].discard(cid)
            for prefix_kind in (P_OBJ, P_DATA, P_OMAP):
                for key, _v in list(self.db.iterate(prefix_kind,
                                                    f"{cid}/")):
                    if not key.startswith(f"{cid}/"):
                        break
                    kvt.rmkey(prefix_kind, key)
            # staged state from earlier ops in this SAME txn must die
            # too, or it resurrects objects into the removed collection
            for key in list(st["omaps"]):
                if key.startswith(f"{cid}/"):
                    st["omaps"][key] = None
            for key in list(heads):
                if key.startswith(f"{cid}/"):
                    heads[key] = None
            for key in list(datas):
                if key.startswith(f"{cid}/"):
                    datas[key] = None
        elif kind == "touch":
            _, cid, oid = op
            self._head_or_new(st, cid, oid, create=True)
        elif kind == "write":
            _, cid, oid, offset, data = op
            head = self._head_or_new(st, cid, oid, create=True)
            self._write_span(datas, cid, oid, offset, data)
            head["size"] = max(head["size"], offset + len(data))
        elif kind == "zero":
            _, cid, oid, offset, length = op
            head = self._head_or_new(st, cid, oid, create=True)
            self._write_span(datas, cid, oid, offset, b"\x00" * length)
            head["size"] = max(head["size"], offset + length)
        elif kind == "truncate":
            _, cid, oid, size = op
            head = self._head_or_new(st, cid, oid, create=True)
            old = head["size"]
            if size < old:
                first_dead = (size + BLOCK - 1) // BLOCK
                for b in range(first_dead, (old + BLOCK - 1) // BLOCK):
                    datas[_dkey(cid, oid, b)] = None
                if size % BLOCK:
                    b = size // BLOCK
                    cur = self._read_block(datas, cid, oid, b)
                    datas[_dkey(cid, oid, b)] = cur[: size % BLOCK]
            head["size"] = size
        elif kind in ("remove", "try_remove"):
            _, cid, oid = op
            key = _okey(cid, oid)
            exists = heads.get(key) is not None if key in heads \
                else self.db.get(P_OBJ, key) is not None
            if not exists:
                if kind == "remove":
                    raise StoreError(ENOENT, f"remove {cid}/{oid}")
                return
            self._purge(st, datas, kvt, cid, oid)
        elif kind in ("clone", "try_clone"):
            _, cid, src, dst = op
            skey = _okey(cid, src)
            if skey in heads:
                src_head = heads[skey]
            else:
                blob = self.db.get(P_OBJ, skey)
                src_head = denc.loads(blob) if blob else None
            if src_head is None:
                if kind == "try_clone":
                    return
                raise StoreError(ENOENT, f"clone src {cid}/{src}")
            self._purge(st, datas, kvt, cid, dst)
            heads[_okey(cid, dst)] = {"size": src_head["size"],
                                      "xattrs": dict(src_head["xattrs"])}
            for b in range((src_head["size"] + BLOCK - 1) // BLOCK):
                blob = self._read_block(datas, cid, src, b)
                if blob:
                    datas[_dkey(cid, dst, b)] = blob
            for k, val in self._omap_items(st, cid, src).items():
                st["omaps"][f"{cid}/{dst}/{k}"] = val
        elif kind == "move":
            _, scid, soid, dcid, doid = op
            skey = _okey(scid, soid)
            if skey in heads:
                src_head = heads[skey]
            else:
                blob = self.db.get(P_OBJ, skey)
                src_head = denc.loads(blob) if blob else None
            if src_head is None:
                raise StoreError(ENOENT, f"move src {scid}/{soid}")
            if dcid not in st["new_colls"] and \
                    self.db.get(P_COLL, dcid) is None:
                raise StoreError(ENOENT, f"no collection {dcid}")
            self._purge(st, datas, kvt, dcid, doid)
            heads[_okey(dcid, doid)] = {
                "size": src_head["size"],
                "xattrs": dict(src_head["xattrs"])}
            for b in range((src_head["size"] + BLOCK - 1) // BLOCK):
                blob = self._read_block(datas, scid, soid, b)
                if blob:
                    datas[_dkey(dcid, doid, b)] = blob
            for k, val in self._omap_items(st, scid, soid).items():
                st["omaps"][f"{dcid}/{doid}/{k}"] = val
            self._purge(st, datas, kvt, scid, soid)
        elif kind == "setattr":
            _, cid, oid, name, value = op
            head = self._head_or_new(st, cid, oid, create=True)
            head["xattrs"][name] = value
        elif kind == "rmattr":
            _, cid, oid, name = op
            head = self._head_or_new(st, cid, oid, create=False)
            head["xattrs"].pop(name, None)
        elif kind == "omap_set":
            _, cid, oid, kvs = op
            self._head_or_new(st, cid, oid, create=True)
            for k, v in kvs.items():
                st["omaps"][f"{cid}/{oid}/{k}"] = v
        elif kind == "omap_rm":
            _, cid, oid, keys = op
            for k in keys:
                st["omaps"][f"{cid}/{oid}/{k}"] = None
        elif kind == "omap_clear":
            _, cid, oid = op
            for k in self._omap_items(st, cid, oid):
                st["omaps"][f"{cid}/{oid}/{k}"] = None
        else:
            raise StoreError(22, f"kstore: unknown op {kind!r}")

    def _purge(self, st, datas, kvt, cid: str, oid: str) -> None:
        heads = st["heads"]
        key = _okey(cid, oid)
        blob = self.db.get(P_OBJ, key)
        size = 0
        if key in heads and heads[key] is not None:
            size = heads[key]["size"]
        elif blob is not None:
            size = denc.loads(blob)["size"]
        heads[key] = None
        for b in range((size + BLOCK - 1) // BLOCK):
            datas[_dkey(cid, oid, b)] = None
        for k in self._omap_items(st, cid, oid):
            st["omaps"][f"{cid}/{oid}/{k}"] = None

    # -- reads -------------------------------------------------------------

    def read(self, cid: str, oid: str, offset: int = 0,
             length: int = 0) -> bytes:
        self._maybe_eio(oid)
        with self._lock:
            head = self._head(cid, oid)
            size = head["size"]
            end = size if length == 0 else min(size, offset + length)
            if offset >= end:
                return b""
            out = bytearray(end - offset)
            pos = offset
            while pos < end:
                block = pos // BLOCK
                boff = pos % BLOCK
                take = min(end - pos, BLOCK - boff)
                blob = self.db.get(P_DATA, _dkey(cid, oid, block)) \
                    or b""
                piece = blob[boff: boff + take]
                out[pos - offset: pos - offset + len(piece)] = piece
                pos += take
            return bytes(out)

    def stat(self, cid: str, oid: str) -> dict:
        with self._lock:
            return {"size": self._head(cid, oid)["size"]}

    def exists(self, cid: str, oid: str) -> bool:
        return self.db.get(P_OBJ, _okey(cid, oid)) is not None

    def getattr(self, cid: str, oid: str, name: str) -> bytes:
        head = self._head(cid, oid)
        if name not in head["xattrs"]:
            raise StoreError(61, f"no xattr {name}")    # ENODATA
        return head["xattrs"][name]

    def getattrs(self, cid: str, oid: str) -> dict[str, bytes]:
        return dict(self._head(cid, oid)["xattrs"])

    def omap_get(self, cid: str, oid: str) -> dict[str, bytes]:
        with self._lock:
            self._head(cid, oid)
            prefix = f"{cid}/{oid}/"
            out = {}
            for key, val in self.db.iterate(P_OMAP, prefix):
                if not key.startswith(prefix):
                    break
                out[key[len(prefix):]] = val
            return out

    def omap_get_values(self, cid: str, oid: str, keys) -> dict:
        omap = self.omap_get(cid, oid)
        return {k: omap[k] for k in keys if k in omap}

    def list_collections(self) -> list[str]:
        return sorted(k for k, _v in self.db.iterate(P_COLL))

    def collection_exists(self, cid: str) -> bool:
        return self.db.get(P_COLL, cid) is not None

    def collection_list(self, cid: str, start: str = "",
                        max_count: int = 0) -> list[str]:
        with self._lock:
            if not self.collection_exists(cid):
                raise StoreError(ENOENT, f"no collection {cid}")
            prefix = f"{cid}/"
            names = []
            # seed the iterator at the cursor: rescanning the whole
            # collection per page would make paging O(N^2/k)
            for key, _v in self.db.iterate(P_OBJ, prefix + start):
                if not key.startswith(prefix):
                    break
                name = key[len(prefix):]
                if start and name <= start:
                    continue       # start is exclusive
                names.append(name)
                if max_count and len(names) >= max_count:
                    break
            return sorted(names)
