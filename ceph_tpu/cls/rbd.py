"""cls_rbd: image header + directory methods (cls/rbd/cls_rbd.cc).

Image metadata lives in the header object's omap: size, order (object
size = 2^order), snapshot table (name -> pool snap id), and arbitrary
image-meta keys.  The rbd_directory object maps image names for `rbd
ls`.  All mutation happens in-OSD so concurrent clients serialize on
the object like the reference.
"""

from __future__ import annotations

from ..utils import denc
from . import RD, WR, ClsError, MethodContext, cls_method

HDR_KEY = "rbd.header"


def _load_hdr(ctx: MethodContext) -> dict:
    blob = ctx.omap_get([HDR_KEY]).get(HDR_KEY)
    if not blob:
        raise ClsError(2, "no rbd header")
    return denc.loads(blob)


def _save_hdr(ctx: MethodContext, hdr: dict) -> None:
    ctx.omap_set({HDR_KEY: denc.dumps(hdr)})


@cls_method("rbd", "create", WR)
def create(ctx: MethodContext) -> None:
    req = denc.loads(ctx.input)
    if ctx.omap_get([HDR_KEY]).get(HDR_KEY):
        raise ClsError(17, "image exists")            # EEXIST
    order = int(req.get("order", 22))
    if not 12 <= order <= 26:
        raise ClsError(22, f"bad order {order}")
    ctx.create()
    _save_hdr(ctx, {"size": int(req["size"]), "order": order,
                    "snaps": {}, "meta": {}})


@cls_method("rbd", "get_info", RD)
def get_info(ctx: MethodContext) -> bytes:
    return denc.dumps(_load_hdr(ctx))


@cls_method("rbd", "set_size", WR)
def set_size(ctx: MethodContext) -> None:
    hdr = _load_hdr(ctx)
    hdr["size"] = int(denc.loads(ctx.input))
    _save_hdr(ctx, hdr)


@cls_method("rbd", "snap_add", WR)
def snap_add(ctx: MethodContext) -> None:
    req = denc.loads(ctx.input)     # {"name":..., "snapid":...}
    hdr = _load_hdr(ctx)
    if req["name"] in hdr["snaps"]:
        raise ClsError(17, f"snap {req['name']} exists")
    hdr["snaps"][req["name"]] = {"id": int(req["snapid"]),
                                 "size": hdr["size"]}
    _save_hdr(ctx, hdr)


@cls_method("rbd", "snap_remove", WR)
def snap_remove(ctx: MethodContext) -> bytes:
    name = denc.loads(ctx.input)
    hdr = _load_hdr(ctx)
    snap = hdr["snaps"].pop(name, None)
    if snap is None:
        raise ClsError(2, f"no snap {name}")
    _save_hdr(ctx, hdr)
    return denc.dumps(snap["id"])


@cls_method("rbd", "metadata_set", WR)
def metadata_set(ctx: MethodContext) -> None:
    req = denc.loads(ctx.input)
    hdr = _load_hdr(ctx)
    hdr["meta"][req["key"]] = req["value"]
    _save_hdr(ctx, hdr)


@cls_method("rbd", "metadata_get", RD)
def metadata_get(ctx: MethodContext) -> bytes:
    key = denc.loads(ctx.input)
    hdr = _load_hdr(ctx)
    if key not in hdr["meta"]:
        raise ClsError(2, f"no metadata {key}")
    return denc.dumps(hdr["meta"][key])


# -- rbd_directory ----------------------------------------------------------

@cls_method("rbd", "dir_add", WR)
def dir_add(ctx: MethodContext) -> None:
    name = denc.loads(ctx.input)
    if ctx.omap_get([f"name.{name}"]).get(f"name.{name}"):
        raise ClsError(17, f"image {name} exists")
    if not ctx.exists():
        ctx.create()
    ctx.omap_set({f"name.{name}": b"1"})


@cls_method("rbd", "dir_remove", WR)
def dir_remove(ctx: MethodContext) -> None:
    name = denc.loads(ctx.input)
    if not ctx.omap_get([f"name.{name}"]).get(f"name.{name}"):
        raise ClsError(2, f"no image {name}")
    ctx.omap_rm([f"name.{name}"])


@cls_method("rbd", "dir_list", RD)
def dir_list(ctx: MethodContext) -> bytes:
    names = sorted(k[len("name."):] for k in ctx.omap_get()
                   if k.startswith("name."))
    return denc.dumps(names)
