"""cls_rbd: image header + directory methods (cls/rbd/cls_rbd.cc).

Image metadata lives in the header object's omap: size, order (object
size = 2^order), snapshot table (name -> pool snap id), and arbitrary
image-meta keys.  The rbd_directory object maps image names for `rbd
ls`.  All mutation happens in-OSD so concurrent clients serialize on
the object like the reference.
"""

from __future__ import annotations

from ..utils import denc
from . import RD, WR, ClsError, MethodContext, cls_method

HDR_KEY = "rbd.header"


def _load_hdr(ctx: MethodContext) -> dict:
    blob = ctx.omap_get([HDR_KEY]).get(HDR_KEY)
    if not blob:
        raise ClsError(2, "no rbd header")
    return denc.loads(blob)


def _save_hdr(ctx: MethodContext, hdr: dict) -> None:
    ctx.omap_set({HDR_KEY: denc.dumps(hdr)})


@cls_method("rbd", "create", WR)
def create(ctx: MethodContext) -> None:
    req = denc.loads(ctx.input)
    if ctx.omap_get([HDR_KEY]).get(HDR_KEY):
        raise ClsError(17, "image exists")            # EEXIST
    order = int(req.get("order", 22))
    if not 12 <= order <= 26:
        raise ClsError(22, f"bad order {order}")
    ctx.create()
    _save_hdr(ctx, {"size": int(req["size"]), "order": order,
                    "snaps": {}, "meta": {}})


@cls_method("rbd", "get_info", RD)
def get_info(ctx: MethodContext) -> bytes:
    return denc.dumps(_load_hdr(ctx))


@cls_method("rbd", "set_size", WR)
def set_size(ctx: MethodContext) -> None:
    hdr = _load_hdr(ctx)
    hdr["size"] = int(denc.loads(ctx.input))
    _save_hdr(ctx, hdr)


@cls_method("rbd", "snap_add", WR)
def snap_add(ctx: MethodContext) -> None:
    req = denc.loads(ctx.input)     # {"name":..., "snapid":...}
    hdr = _load_hdr(ctx)
    if req["name"] in hdr["snaps"]:
        raise ClsError(17, f"snap {req['name']} exists")
    hdr["snaps"][req["name"]] = {"id": int(req["snapid"]),
                                 "size": hdr["size"]}
    _save_hdr(ctx, hdr)


@cls_method("rbd", "snap_remove", WR)
def snap_remove(ctx: MethodContext) -> bytes:
    name = denc.loads(ctx.input)
    hdr = _load_hdr(ctx)
    snap = hdr["snaps"].pop(name, None)
    if snap is None:
        raise ClsError(2, f"no snap {name}")
    _save_hdr(ctx, hdr)
    return denc.dumps(snap["id"])


@cls_method("rbd", "metadata_set", WR)
def metadata_set(ctx: MethodContext) -> None:
    req = denc.loads(ctx.input)
    hdr = _load_hdr(ctx)
    hdr["meta"][req["key"]] = req["value"]
    _save_hdr(ctx, hdr)


@cls_method("rbd", "metadata_get", RD)
def metadata_get(ctx: MethodContext) -> bytes:
    key = denc.loads(ctx.input)
    hdr = _load_hdr(ctx)
    if key not in hdr["meta"]:
        raise ClsError(2, f"no metadata {key}")
    return denc.dumps(hdr["meta"][key])


# -- rbd_directory ----------------------------------------------------------

@cls_method("rbd", "dir_add", WR)
def dir_add(ctx: MethodContext) -> None:
    name = denc.loads(ctx.input)
    if ctx.omap_get([f"name.{name}"]).get(f"name.{name}"):
        raise ClsError(17, f"image {name} exists")
    if not ctx.exists():
        ctx.create()
    ctx.omap_set({f"name.{name}": b"1"})


@cls_method("rbd", "dir_remove", WR)
def dir_remove(ctx: MethodContext) -> None:
    name = denc.loads(ctx.input)
    if not ctx.omap_get([f"name.{name}"]).get(f"name.{name}"):
        raise ClsError(2, f"no image {name}")
    ctx.omap_rm([f"name.{name}"])


@cls_method("rbd", "dir_list", RD)
def dir_list(ctx: MethodContext) -> bytes:
    names = sorted(k[len("name."):] for k in ctx.omap_get()
                   if k.startswith("name."))
    return denc.dumps(names)


# -- layering: parent spec, snap protection, children index -----------------
# (cls/rbd/cls_rbd.cc set_parent/remove_parent/get_protection_status/
#  set_protection_status + child_attach semantics, reduced)

@cls_method("rbd", "set_parent", WR)
def set_parent(ctx: MethodContext) -> None:
    spec = denc.loads(ctx.input)   # {"pool","image","snap","snap_id",
    hdr = _load_hdr(ctx)           #  "overlap"}
    if hdr.get("parent"):
        raise ClsError(17, "parent already set")
    hdr["parent"] = dict(spec)
    _save_hdr(ctx, hdr)


@cls_method("rbd", "remove_parent", WR)
def remove_parent(ctx: MethodContext) -> None:
    hdr = _load_hdr(ctx)
    if not hdr.get("parent"):
        raise ClsError(2, "no parent")
    hdr["parent"] = None
    _save_hdr(ctx, hdr)


@cls_method("rbd", "snap_protect", WR)
def snap_protect(ctx: MethodContext) -> None:
    name = denc.loads(ctx.input)
    hdr = _load_hdr(ctx)
    if name not in hdr["snaps"]:
        raise ClsError(2, f"no snap {name}")
    hdr["snaps"][name]["protected"] = True
    _save_hdr(ctx, hdr)


@cls_method("rbd", "snap_unprotect", WR)
def snap_unprotect(ctx: MethodContext) -> None:
    name = denc.loads(ctx.input)
    hdr = _load_hdr(ctx)
    if name not in hdr["snaps"]:
        raise ClsError(2, f"no snap {name}")
    hdr["snaps"][name]["protected"] = False
    _save_hdr(ctx, hdr)


# rbd_children object: (parent image, snap) -> child specs, kept in the
# PARENT pool so unprotect can refuse while clones exist

def _child_key(req: dict) -> str:
    return f"child.{req['image']}.{req['snap']}"


@cls_method("rbd", "child_add", WR)
def child_add(ctx: MethodContext) -> None:
    req = denc.loads(ctx.input)    # {"image","snap","child_pool",
    if not ctx.exists():           #  "child_image"}
        ctx.create()
    key = _child_key(req)
    kids = denc.loads(ctx.omap_get([key]).get(key) or denc.dumps([]))
    ref = [req["child_pool"], req["child_image"]]
    if ref not in kids:
        kids.append(ref)
    ctx.omap_set({key: denc.dumps(kids)})


@cls_method("rbd", "child_remove", WR)
def child_remove(ctx: MethodContext) -> None:
    req = denc.loads(ctx.input)
    key = _child_key(req)
    kids = denc.loads(ctx.omap_get([key]).get(key) or denc.dumps([]))
    ref = [req["child_pool"], req["child_image"]]
    if ref in kids:
        kids.remove(ref)
    if kids:
        ctx.omap_set({key: denc.dumps(kids)})
    else:
        ctx.omap_rm([key])


@cls_method("rbd", "children_list", RD)
def children_list(ctx: MethodContext) -> bytes:
    req = denc.loads(ctx.input)
    key = _child_key(req)
    if not ctx.exists():
        return denc.dumps([])
    return denc.dumps(
        denc.loads(ctx.omap_get([key]).get(key) or denc.dumps([])))


@cls_method("rbd", "set_parent_overlap", WR)
def set_parent_overlap(ctx: MethodContext) -> None:
    """Shrink the parent overlap (librbd shrink semantics: a resize
    below the overlap permanently reduces what the parent backs)."""
    n = int(denc.loads(ctx.input))
    hdr = _load_hdr(ctx)
    if not hdr.get("parent"):
        raise ClsError(2, "no parent")
    hdr["parent"]["overlap"] = min(hdr["parent"]["overlap"], n)
    _save_hdr(ctx, hdr)
