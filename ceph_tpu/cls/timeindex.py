"""cls_timeindex: time-keyed index objects (cls/timeindex/
cls_timeindex.cc semantics): entries keyed by (stamp, name) for
ranged time-window queries — RGW's sync-status and usage indexes
lean on it.
"""

from __future__ import annotations

from ..utils import denc
from . import RD, WR, ClsError, MethodContext, cls_method, page_omap


def _key(stamp: float, name: str) -> str:
    return f"{int(stamp * 1e6):017d}~{name}"


@cls_method("timeindex", "add", WR)
def add(ctx: MethodContext) -> None:
    """{"entries": [{"name", "value", "stamp"?}]}."""
    req = denc.loads(ctx.input)
    if not ctx.exists():
        ctx.create()
    out = {}
    for ent in req.get("entries", []):
        stamp = (float(ent["stamp"]) if ent.get("stamp") is not None
                 else ctx.now())
        out[_key(stamp, str(ent.get("name", "")))] = denc.dumps({
            "stamp": stamp,
            "name": str(ent.get("name", "")),
            "value": bytes(ent.get("value", b"")),
        })
    if out:
        ctx.omap_set(out)


@cls_method("timeindex", "list", RD)
def list_entries(ctx: MethodContext) -> bytes:
    """{"from"?, "to"?, "marker"?, "max_entries"?} -> page of entries
    within the [from, to) stamp window."""
    req = denc.loads(ctx.input) if ctx.input else {}
    lo = _key(float(req.get("from", 0.0)), "")
    hi = _key(float(req["to"]), "") if "to" in req else "\x7f"
    marker = str(req.get("marker", "")) or lo
    return denc.dumps(page_omap(
        ctx.omap_get(None), marker, hi,
        int(req.get("max_entries", 1000))))


@cls_method("timeindex", "trim", WR)
def trim(ctx: MethodContext) -> None:
    """{"from"?, "to"}: drop entries with stamp in [from, to)."""
    req = denc.loads(ctx.input)
    if "to" not in req:
        raise ClsError(22, "timeindex.trim needs to")
    lo = _key(float(req.get("from", 0.0)), "")
    hi = _key(float(req["to"]), "")
    omap = ctx.omap_get(None)
    victims = [k for k in omap
               if not k.startswith("\x00") and lo <= k < hi]
    if victims:
        ctx.omap_rm(victims)
