"""cls_numops: atomic arithmetic on omap values (cls/numops/
cls_numops.cc semantics): read-modify-write of a numeric cell happens
in ONE in-OSD op, so concurrent adders never lose updates.
"""

from __future__ import annotations

from ..utils import denc
from . import WR, ClsError, MethodContext, cls_method


def _apply(ctx: MethodContext, key: str, fn) -> bytes:
    if not ctx.exists():
        ctx.create()
    raw = ctx.omap_get([key]).get(key)
    try:
        cur = float(raw) if raw is not None else 0.0
    except ValueError:
        raise ClsError(22, f"non-numeric value at {key!r}")
    new = fn(cur)
    rep = repr(int(new)) if float(new).is_integer() else repr(new)
    ctx.omap_set({key: rep.encode()})
    return denc.dumps(float(new))


@cls_method("numops", "add", WR)
def add(ctx: MethodContext) -> bytes:
    """{"key", "value"} -> new value (missing cell counts as 0)."""
    req = denc.loads(ctx.input)
    return _apply(ctx, str(req["key"]),
                  lambda cur: cur + float(req.get("value", 0)))


@cls_method("numops", "sub", WR)
def sub(ctx: MethodContext) -> bytes:
    req = denc.loads(ctx.input)
    return _apply(ctx, str(req["key"]),
                  lambda cur: cur - float(req.get("value", 0)))


@cls_method("numops", "mul", WR)
def mul(ctx: MethodContext) -> bytes:
    req = denc.loads(ctx.input)
    return _apply(ctx, str(req["key"]),
                  lambda cur: cur * float(req.get("value", 1)))
