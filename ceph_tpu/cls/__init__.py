"""Object classes: in-OSD RPC methods (objclass/objclass.h analog).

The reference loads .so classes via ClassHandler::open_class
(osd/ClassHandler.cc:143) and methods register with
cls_register_cxx_method (objclass/objclass.h:73,137); a client's
CEPH_OSD_OP_CALL executes the method INSIDE the OSD against the target
object.  Here classes are python modules registered at import, and a
method receives a MethodContext bound to the object: reads hit the
store directly, writes append to the op's transaction so they
replicate exactly like any other mutation.

Method flags mirror the reference: RD (reads object state) and WR
(mutates it) — WR methods run on the write path and their transaction
fans out to replicas.
"""

from __future__ import annotations

from typing import Callable

RD = 1
WR = 2


class ClsError(Exception):
    def __init__(self, errno_: int, msg: str = ""):
        super().__init__(msg or f"errno {errno_}")
        self.errno = errno_


class MethodContext:
    """What a class method may do to its object (cls_cxx_* surface)."""

    def __init__(self, pg, txn, oid: str, inp: bytes):
        self._pg = pg
        self._txn = txn              # None for RD methods
        self._store = pg.osd.store
        self.oid = oid
        self.input = inp
        self.removed = False         # method removed its object

    # -- reads -------------------------------------------------------------

    def now(self) -> float:
        """Daemon time through the injectable Clock — cls methods run
        inside the OSD and must stay deterministic under ManualClock."""
        return self._pg.osd.clock.now()

    def exists(self) -> bool:
        return self._store.exists(self._pg.cid, self.oid)

    def read(self, offset: int = 0, length: int = 0) -> bytes:
        from ..store.objectstore import StoreError
        try:
            return self._store.read(self._pg.cid, self.oid, offset, length)
        except StoreError as e:
            raise ClsError(e.errno, str(e))

    def stat(self) -> dict:
        from ..store.objectstore import StoreError
        try:
            return self._store.stat(self._pg.cid, self.oid)
        except StoreError as e:
            raise ClsError(e.errno, str(e))

    def getxattr(self, name: str) -> bytes | None:
        from ..store.objectstore import StoreError
        try:
            return self._store.getattr(self._pg.cid, self.oid,
                                       "u." + name)
        except StoreError:
            return None

    def omap_get(self, keys=None) -> dict:
        from ..store.objectstore import StoreError
        try:
            omap = self._store.omap_get(self._pg.cid, self.oid)
        except StoreError:
            return {}
        if keys is None:
            return omap
        return {k: omap[k] for k in keys if k in omap}

    # -- writes (WR methods only) ------------------------------------------

    def _wr(self):
        if self._txn is None:
            raise ClsError(30, "write from RD method")     # EROFS

    def create(self) -> None:
        self._wr()
        self._txn.touch(self._pg.cid, self.oid)

    def write(self, offset: int, data: bytes) -> None:
        self._wr()
        self._txn.write(self._pg.cid, self.oid, offset, bytes(data))

    def write_full(self, data: bytes) -> None:
        self._wr()
        self._txn.truncate(self._pg.cid, self.oid, 0)
        self._txn.write(self._pg.cid, self.oid, 0, bytes(data))

    def truncate(self, size: int) -> None:
        self._wr()
        self._txn.truncate(self._pg.cid, self.oid, size)

    def remove(self) -> None:
        self._wr()
        self._txn.remove(self._pg.cid, self.oid)
        self.removed = True

    def setxattr(self, name: str, value: bytes) -> None:
        self._wr()
        self._txn.setattr(self._pg.cid, self.oid, "u." + name,
                          bytes(value))

    def omap_set(self, kv: dict) -> None:
        self._wr()
        self._txn.omap_setkeys(self._pg.cid, self.oid, kv)

    def omap_rm(self, keys) -> None:
        self._wr()
        self._txn.omap_rmkeys(self._pg.cid, self.oid, list(keys))


def page_omap(omap: dict, marker: str, hi: str,
              limit: int) -> dict:
    """Shared marker-paged listing over an omap snapshot (used by the
    log and timeindex classes): entries strictly after `marker` and
    below `hi`, meta (\x00-prefixed) keys excluded."""
    from ..utils import denc
    keys = sorted(k for k in omap
                  if not k.startswith("\x00")
                  and k > marker and k < hi)
    page = keys[:limit]
    return {
        "entries": [dict(denc.loads(omap[k]), marker=k)
                    for k in page],
        "marker": page[-1] if page else marker,
        "truncated": len(keys) > limit,
    }


class ClassRegistry:
    """ClassHandler + per-class method tables."""

    def __init__(self):
        self._methods: dict[tuple[str, str], tuple[Callable, int]] = {}

    def register(self, cls: str, method: str, flags: int,
                 fn: Callable[[MethodContext], bytes | None]) -> None:
        self._methods[(cls, method)] = (fn, flags)

    def get(self, cls: str, method: str):
        return self._methods.get((cls, method))

    def is_write(self, cls: str, method: str) -> bool:
        ent = self._methods.get((cls, method))
        return bool(ent and ent[1] & WR)

    def classes(self) -> list[str]:
        return sorted({c for c, _m in self._methods})


registry = ClassRegistry()


def cls_method(cls: str, method: str, flags: int):
    """Decorator: the cls_register_cxx_method analog."""
    def wrap(fn):
        registry.register(cls, method, flags, fn)
        return fn
    return wrap


# built-in classes (the reference preloads its cls .so set at OSD boot)
from . import (hello, kvstore, lock, log, numops, rbd,  # noqa: E402,F401
               refcount, timeindex, version)  # noqa: E402,F401
