"""cls_version: object version cells with conditional checks
(cls/version/cls_version.cc semantics).

RGW leans on this for metadata-cache coherence: every mutation bumps
(ver, tag); readers compare.  Conditions mirror the reference's
VER_COND_* set; a failed condition is ECANCELED so callers can retry
their read-modify-write.
"""

from __future__ import annotations

import uuid

from ..utils import denc
from . import RD, WR, ClsError, MethodContext, cls_method

XATTR = "obj_version"

EQ, GT, GE, LT, LE, TAG_EQ, TAG_NE = (
    "eq", "gt", "ge", "lt", "le", "tag_eq", "tag_ne")


def _read_ver(ctx: MethodContext) -> dict:
    blob = ctx.getxattr(XATTR)
    if blob is None:
        return {"ver": 0, "tag": ""}
    return denc.loads(blob)


def _check(cur: dict, conds: list) -> None:
    for cond in conds:
        op, ver, tag = cond.get("op"), cond.get("ver", 0), \
            cond.get("tag", "")
        ok = {
            EQ: cur["ver"] == ver,
            GT: cur["ver"] > ver,
            GE: cur["ver"] >= ver,
            LT: cur["ver"] < ver,
            LE: cur["ver"] <= ver,
            TAG_EQ: cur["tag"] == tag,
            TAG_NE: cur["tag"] != tag,
        }.get(op)
        if ok is None:
            raise ClsError(22, f"bad version cond {op!r}")
        if not ok:
            raise ClsError(125, f"version cond {op} failed "
                                f"(cur v{cur['ver']} tag "
                                f"{cur['tag']!r})")     # ECANCELED


@cls_method("version", "set", WR)
def set_ver(ctx: MethodContext) -> None:
    """{"ver": int, "tag": str} — pin an explicit version."""
    req = denc.loads(ctx.input)
    if not ctx.exists():
        ctx.create()
    ctx.setxattr(XATTR, denc.dumps(
        {"ver": int(req.get("ver", 0)),
         "tag": str(req.get("tag", ""))}))


@cls_method("version", "inc", WR)
def inc(ctx: MethodContext) -> bytes:
    """{"conds": [...]} — bump ver (mint a tag on first touch) after
    the conditions hold.  Returns the new version."""
    req = denc.loads(ctx.input) if ctx.input else {}
    cur = _read_ver(ctx)
    _check(cur, req.get("conds", []))
    if not ctx.exists():
        ctx.create()
    new = {"ver": cur["ver"] + 1,
           "tag": cur["tag"] or uuid.uuid4().hex[:16]}
    ctx.setxattr(XATTR, denc.dumps(new))
    return denc.dumps(new)


@cls_method("version", "read", RD)
def read(ctx: MethodContext) -> bytes:
    return denc.dumps(_read_ver(ctx))


@cls_method("version", "check", RD)
def check(ctx: MethodContext) -> None:
    """{"conds": [...]} — pure conditional gate (readers pair it with
    a read op in one exec)."""
    req = denc.loads(ctx.input)
    _check(_read_ver(ctx), req.get("conds", []))
