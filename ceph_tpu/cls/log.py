"""cls_log: timestamped log objects (cls/log/cls_log.cc semantics).

RGW's metadata/data changelogs ride this: add entries stamped with a
monotonic section+timestamp key, list from a marker, trim up to a
bound.  Entries land in the omap keyed ``<stamp>_<seq>`` so listing is
a ranged read in time order.
"""

from __future__ import annotations

from ..utils import denc
from . import RD, WR, ClsError, MethodContext, cls_method, page_omap

SEQ_KEY = "\x00seq"


def _entry_key(stamp: float, seq: int) -> str:
    return f"{int(stamp * 1e6):017d}_{seq:012d}"


@cls_method("log", "add", WR)
def add(ctx: MethodContext) -> bytes:
    """{"entries": [{"section", "name", "data", "stamp"?}]} -> count.
    Stamps default to now; the per-object seq breaks same-tick ties."""
    req = denc.loads(ctx.input)
    if not ctx.exists():
        ctx.create()
    cur = ctx.omap_get([SEQ_KEY])
    seq = int(cur.get(SEQ_KEY, b"0"))
    out = {}
    for ent in req.get("entries", []):
        seq += 1
        stamp = (float(ent["stamp"]) if ent.get("stamp") is not None
                 else ctx.now())
        out[_entry_key(stamp, seq)] = denc.dumps({
            "section": str(ent.get("section", "")),
            "name": str(ent.get("name", "")),
            "stamp": stamp,
            "data": bytes(ent.get("data", b"")),
        })
    out[SEQ_KEY] = str(seq).encode()
    ctx.omap_set(out)
    return denc.dumps(len(out) - 1)


@cls_method("log", "list", RD)
def list_entries(ctx: MethodContext) -> bytes:
    """{"marker"?, "max_entries"?} -> {"entries": [...], "marker",
    "truncated"}.  Markers are opaque entry keys."""
    req = denc.loads(ctx.input) if ctx.input else {}
    return denc.dumps(page_omap(
        ctx.omap_get(None), str(req.get("marker", "")), "\x7f",
        int(req.get("max_entries", 1000))))


@cls_method("log", "trim", WR)
def trim(ctx: MethodContext) -> None:
    """{"to_marker"}: drop every entry at or before the marker."""
    req = denc.loads(ctx.input)
    to = str(req.get("to_marker", ""))
    if not to:
        raise ClsError(22, "log.trim needs to_marker")
    omap = ctx.omap_get(None)
    victims = [k for k in omap
               if not k.startswith("\x00") and k <= to]
    if victims:
        ctx.omap_rm(victims)
