"""cls_refcount: shared-object reference counting (cls/refcount/
cls_refcount.cc semantics).

RGW-style dedup: several logical objects point at one RADOS object,
each holding a distinct TAG.  `get` adds a tag, `put` drops one and
REMOVES the object when the last tag goes; `set` replaces the whole
tag set (migration/repair).  An untagged object (never ref-counted)
defaults to one implicit reference, matching the reference's
implicit_ref behavior: a bare `put` on it removes it.
"""

from __future__ import annotations

from ..utils import denc
from . import RD, WR, ClsError, MethodContext, cls_method

XATTR = "refcount"


def _read_refs(ctx: MethodContext) -> list[str] | None:
    blob = ctx.getxattr(XATTR)
    if blob is None:
        return None
    refs = denc.loads(blob)
    if not isinstance(refs, list):
        raise ClsError(5, "corrupt refcount xattr")
    return refs


@cls_method("refcount", "get", WR)
def get(ctx: MethodContext) -> None:
    """{"tag": str} — add a reference."""
    req = denc.loads(ctx.input)
    tag = str(req.get("tag", ""))
    if not tag:
        raise ClsError(22, "refcount.get needs a tag")
    if not ctx.exists():
        raise ClsError(2, "no such object")
    refs = _read_refs(ctx) or []
    if tag not in refs:
        refs.append(tag)
    ctx.setxattr(XATTR, denc.dumps(refs))


@cls_method("refcount", "put", WR)
def put(ctx: MethodContext) -> bytes:
    """{"tag": str} — drop a reference; removes the object when the
    last one goes.  Returns the remaining count."""
    req = denc.loads(ctx.input)
    tag = str(req.get("tag", ""))
    if not ctx.exists():
        raise ClsError(2, "no such object")
    refs = _read_refs(ctx)
    if refs is None:
        # implicit single reference (cls_refcount implicit_ref): any
        # put on a never-tagged object releases it
        ctx.remove()
        return denc.dumps(0)
    if tag in refs:
        refs.remove(tag)
    elif req.get("strict"):
        raise ClsError(2, f"no such tag {tag!r}")
    if refs:
        ctx.setxattr(XATTR, denc.dumps(refs))
    else:
        ctx.remove()
    return denc.dumps(len(refs))


@cls_method("refcount", "set", WR)
def set_refs(ctx: MethodContext) -> None:
    """{"refs": [tags]} — replace the tag set outright."""
    req = denc.loads(ctx.input)
    refs = [str(t) for t in req.get("refs", [])]
    if not ctx.exists():
        raise ClsError(2, "no such object")
    if not refs:
        ctx.remove()
        return
    ctx.setxattr(XATTR, denc.dumps(refs))


@cls_method("refcount", "read", RD)
def read(ctx: MethodContext) -> bytes:
    """-> [tags] (empty list = implicit single ref)."""
    if not ctx.exists():
        raise ClsError(2, "no such object")
    return denc.dumps(_read_refs(ctx) or [])
