"""cls_hello: the reference's example/test class (cls/hello/cls_hello.cc)."""

from __future__ import annotations

from ..utils import denc
from . import RD, WR, ClsError, MethodContext, cls_method


@cls_method("hello", "say_hello", RD)
def say_hello(ctx: MethodContext) -> bytes:
    name = ctx.input.decode() if ctx.input else "world"
    return f"Hello, {name}!".encode()


@cls_method("hello", "record_hello", WR)
def record_hello(ctx: MethodContext) -> bytes | None:
    """Writes a greeting into the object (exercises the WR path)."""
    name = ctx.input.decode() if ctx.input else "world"
    if ctx.exists() and ctx.read():
        raise ClsError(17, "already greeted")        # EEXIST
    ctx.write_full(f"Hello, {name}!".encode())
    return None


@cls_method("hello", "replay", RD)
def replay(ctx: MethodContext) -> bytes:
    return ctx.read()


@cls_method("hello", "turn_it_to_11", WR)
def turn_it_to_11(ctx: MethodContext) -> bytes:
    """Uppercases the object in place (read + write in one method)."""
    data = ctx.read()
    ctx.write_full(data.upper())
    return denc.dumps(len(data))
