"""cls_lock: advisory object locks (cls/lock/cls_lock.cc semantics).

Locks live in an omap-backed table on the object: name -> {type,
holders: {(entity, cookie): tag}}.  Exclusive locks admit one holder;
shared locks admit many.  librbd's exclusive-lock feature is built on
exactly this class in the reference.
"""

from __future__ import annotations

from ..utils import denc
from . import RD, WR, ClsError, MethodContext, cls_method

LOCK_KEY = "lock.state"
EXCLUSIVE = "exclusive"
SHARED = "shared"


def _load(ctx: MethodContext) -> dict:
    blob = ctx.omap_get([LOCK_KEY]).get(LOCK_KEY)
    return denc.loads(blob) if blob else {}


def _save(ctx: MethodContext, locks: dict) -> None:
    ctx.omap_set({LOCK_KEY: denc.dumps(locks)})


@cls_method("lock", "lock", WR)
def lock(ctx: MethodContext) -> None:
    req = denc.loads(ctx.input)
    name, ltype = req["name"], req.get("type", EXCLUSIVE)
    holder = (req["entity"], req.get("cookie", ""))
    locks = _load(ctx)
    cur = locks.get(name)
    if cur is not None:
        holders = {tuple(h) for h in cur["holders"]}
        if holder in holders:
            raise ClsError(17, "already held by you")       # EEXIST
        if cur["type"] == EXCLUSIVE or ltype == EXCLUSIVE:
            raise ClsError(16, f"lock {name} held")         # EBUSY
        holders.add(holder)
        cur["holders"] = sorted(list(h) for h in holders)
    else:
        locks[name] = {"type": ltype, "holders": [list(holder)],
                       "tag": req.get("tag", "")}
    if not ctx.exists():
        ctx.create()
    _save(ctx, locks)


def _remove_holder(ctx: MethodContext, errmsg: str) -> None:
    req = denc.loads(ctx.input)
    name = req["name"]
    holder = [req["entity"], req.get("cookie", "")]
    locks = _load(ctx)
    cur = locks.get(name)
    if cur is None or holder not in cur["holders"]:
        raise ClsError(2, errmsg.format(name=name, holder=holder))
    cur["holders"].remove(holder)
    if not cur["holders"]:
        del locks[name]
    _save(ctx, locks)


@cls_method("lock", "unlock", WR)
def unlock(ctx: MethodContext) -> None:
    _remove_holder(ctx, "lock {name} not held by {holder}")


@cls_method("lock", "break_lock", WR)
def break_lock(ctx: MethodContext) -> None:
    """Forcibly evict ANOTHER client's holder (admin/failover path —
    same mechanics as unlock; the caller names the victim)."""
    _remove_holder(ctx, "lock {name}: no such holder {holder}")


@cls_method("lock", "get_info", RD)
def get_info(ctx: MethodContext) -> bytes:
    req = denc.loads(ctx.input) if ctx.input else {}
    locks = _load(ctx)
    name = req.get("name")
    return denc.dumps(locks.get(name) if name else locks)
