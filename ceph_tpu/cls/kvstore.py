"""cls_kvstore: a flat distributed KV service over object omaps — the
key_value_store/kv_flat_btree_async.cc analog at its useful core:
server-side conditional updates so concurrent clients serialize in-OSD
instead of read-modify-writing racily."""

from __future__ import annotations

from ..utils import denc
from . import RD, WR, ClsError, MethodContext, cls_method


@cls_method("kvstore", "put", WR)
def put(ctx: MethodContext) -> None:
    req = denc.loads(ctx.input)      # {"kv": {k: v}, "if_absent": bool}
    if not ctx.exists():
        ctx.create()
    if req.get("if_absent"):
        cur = ctx.omap_get(list(req["kv"]))
        dup = [k for k in req["kv"] if k in cur]
        if dup:
            raise ClsError(17, f"keys exist: {dup}")
    ctx.omap_set({k: bytes(v) for k, v in req["kv"].items()})


@cls_method("kvstore", "get", RD)
def get(ctx: MethodContext) -> bytes:
    keys = denc.loads(ctx.input)
    return denc.dumps(ctx.omap_get(keys if keys else None))


@cls_method("kvstore", "rm", WR)
def rm(ctx: MethodContext) -> None:
    keys = denc.loads(ctx.input)
    cur = ctx.omap_get(keys)
    missing = [k for k in keys if k not in cur]
    if missing:
        raise ClsError(2, f"no such keys: {missing}")
    ctx.omap_rm(keys)


@cls_method("kvstore", "cas", WR)
def cas(ctx: MethodContext) -> None:
    """Compare-and-swap one key (the btree-split building block)."""
    req = denc.loads(ctx.input)      # {"key", "expect": bytes|None, "value"}
    cur = ctx.omap_get([req["key"]]).get(req["key"])
    expect = req.get("expect")
    if cur != (bytes(expect) if expect is not None else None):
        raise ClsError(125, "compare failed")         # ECANCELED
    if not ctx.exists():
        ctx.create()
    ctx.omap_set({req["key"]: bytes(req["value"])})


# -- flat-btree primitives (kv_flat_btree_async.cc's in-OSD helpers) -----
#
# The distributed B-tree (client/kv_btree.py) serializes its structural
# races inside the OSD: every leaf mutation is guarded by the leaf's
# version cell, and index transitions are single-round-trip
# check-and-apply ops, so a concurrent split/merge can never interleave
# half-applied with a write (the reference's assert_version +
# prefix-marked index updates, kv_flat_btree_async.cc:585).


def _check_guards(cur: dict, guards: dict, what: str) -> None:
    """Every guard cell must hold its expected value (None = absent),
    else ECANCELED — the structure changed under the caller."""
    for gk, expect in guards.items():
        have = cur.get(gk)
        want = bytes(expect) if expect is not None else None
        if have != want:
            raise ClsError(125, f"{what} {gk!r} mismatch")


@cls_method("kvstore", "put_guarded", WR)
def put_guarded(ctx: MethodContext) -> bytes:
    """{"kv", "guard": {key: expect|None}} -> entry count after write.

    ECANCELED when any guard cell differs — the leaf was split/merged/
    killed under us and the caller must re-walk the index.
    """
    req = denc.loads(ctx.input)
    if not ctx.exists():
        ctx.create()
    # one full read serves guards AND the size answer (omap_get reads
    # the store, not this txn, so the count must be computed from the
    # pre-image + this write's keys)
    cur = ctx.omap_get(None)
    _check_guards(cur, req.get("guard", {}), "guard")
    ctx.omap_set({k: bytes(v) for k, v in req["kv"].items()})
    keys = set(cur) | set(req["kv"])
    return denc.dumps(sum(1 for k in keys if not k.startswith("\x00")))


@cls_method("kvstore", "rm_guarded", WR)
def rm_guarded(ctx: MethodContext) -> bytes:
    """{"keys", "guard": {...}} -> entry count after removal.  ENOENT
    when a key is absent; ECANCELED on guard mismatch."""
    req = denc.loads(ctx.input)
    cur = ctx.omap_get(None)
    _check_guards(cur, req.get("guard", {}), "guard")
    missing = [k for k in req["keys"] if k not in cur]
    if missing:
        raise ClsError(2, f"no such keys: {missing}")
    ctx.omap_rm(req["keys"])
    keys = set(cur) - set(req["keys"])
    return denc.dumps(sum(1 for k in keys if not k.startswith("\x00")))


@cls_method("kvstore", "update_index", WR)
def update_index(ctx: MethodContext) -> None:
    """Atomic index transition: {"expect": {key: blob|None},
    "set": {key: blob}, "rm": [keys]}.  All expectations must hold or
    nothing applies (the split/merge commit point)."""
    req = denc.loads(ctx.input)
    if not ctx.exists():
        ctx.create()
    cur = ctx.omap_get(list(req.get("expect", {})))
    _check_guards(cur, req.get("expect", {}), "index expect")
    if req.get("rm"):
        present = ctx.omap_get(req["rm"])
        ctx.omap_rm([k for k in req["rm"] if k in present])
    if req.get("set"):
        ctx.omap_set({k: bytes(v) for k, v in req["set"].items()})


@cls_method("kvstore", "append_log", WR)
def append_log(ctx: MethodContext) -> bytes:
    """{"entry": bytes} -> seq.  Atomic sequenced append (the cls_rgw
    bilog/cls_log pattern): seq allocation and the entry write happen
    in ONE in-OSD op, so concurrent writers can neither collide on a
    seq nor clobber each other's entries."""
    req = denc.loads(ctx.input)
    if not ctx.exists():
        ctx.create()
    cur = ctx.omap_get(["\x00seq"])
    seq = int(cur.get("\x00seq", b"0")) + 1
    ctx.omap_set({"\x00seq": str(seq).encode(),
                  f"{seq:020d}": bytes(req["entry"])})
    return denc.dumps(seq)
