"""cls_kvstore: a flat distributed KV service over object omaps — the
key_value_store/kv_flat_btree_async.cc analog at its useful core:
server-side conditional updates so concurrent clients serialize in-OSD
instead of read-modify-writing racily."""

from __future__ import annotations

from ..utils import denc
from . import RD, WR, ClsError, MethodContext, cls_method


@cls_method("kvstore", "put", WR)
def put(ctx: MethodContext) -> None:
    req = denc.loads(ctx.input)      # {"kv": {k: v}, "if_absent": bool}
    if not ctx.exists():
        ctx.create()
    if req.get("if_absent"):
        cur = ctx.omap_get(list(req["kv"]))
        dup = [k for k in req["kv"] if k in cur]
        if dup:
            raise ClsError(17, f"keys exist: {dup}")
    ctx.omap_set({k: bytes(v) for k, v in req["kv"].items()})


@cls_method("kvstore", "get", RD)
def get(ctx: MethodContext) -> bytes:
    keys = denc.loads(ctx.input)
    return denc.dumps(ctx.omap_get(keys if keys else None))


@cls_method("kvstore", "rm", WR)
def rm(ctx: MethodContext) -> None:
    keys = denc.loads(ctx.input)
    cur = ctx.omap_get(keys)
    missing = [k for k in keys if k not in cur]
    if missing:
        raise ClsError(2, f"no such keys: {missing}")
    ctx.omap_rm(keys)


@cls_method("kvstore", "cas", WR)
def cas(ctx: MethodContext) -> None:
    """Compare-and-swap one key (the btree-split building block)."""
    req = denc.loads(ctx.input)      # {"key", "expect": bytes|None, "value"}
    cur = ctx.omap_get([req["key"]]).get(req["key"])
    expect = req.get("expect")
    if cur != (bytes(expect) if expect is not None else None):
        raise ClsError(125, "compare failed")         # ECANCELED
    if not ctx.exists():
        ctx.create()
    ctx.omap_set({req["key"]: bytes(req["value"])})
