"""RGW: S3-dialect HTTP object gateway (rgw/rgw_main.cc, rgw_rest_s3.cc
reduced to the core object workflow).

The reference fronts RADOS with civetweb/asio frontends, a REST dialect
layer, and cls_rgw-maintained bucket indexes.  This gateway keeps that
shape: a threaded stdlib HTTP frontend, bucket metadata + per-bucket
indexes in omaps (mutated server-side), object data striped into the
data pool, and signature auth in both AWS v2 and v4 dialects
(auth_v4.py; rgw/rgw_auth_s3.h:24-32).  Object versioning follows
rgw/rgw_op.h:484-493 (RGWGetBucketVersioning/RGWSetBucketVersioning)
and RGWDeleteObj's delete-marker path: versioned buckets stack
versions per key, a plain DELETE plants a marker, and deleting the
marker restores the previous version.  Every mutation appends to a
per-bucket replication log (the cls_rgw bilog analog, served at
``?bilog&marker=N``) that feeds the multisite sync agent
(rgw/sync.py).  The Swift v1 dialect (rgw/swift.py, TempAuth +
container/object ops over the SAME namespace) serves /auth/v1.0 and
/v1/* requests that don't carry AWS signatures.  Lifecycle is out of
scope.

S3 surface:
    GET  /                          ListAllMyBuckets
    PUT  /bucket                    create bucket
    DELETE /bucket                  delete (must be empty)
    GET  /bucket?prefix=&max-keys=&marker=   ListBucket (paginated:
                                    NextMarker continuation, index read
                                    via ranged omap — O(page), not
                                    O(bucket))
    GET  /bucket?uploads            list in-progress multipart uploads
    PUT  /bucket/key                put object
    GET|HEAD /bucket/key            get/stat object
    DELETE /bucket/key              delete object
    POST /bucket/key?uploads        InitiateMultipartUpload
    PUT  /bucket/key?uploadId=&partNumber=   UploadPart
    POST /bucket/key?uploadId=      CompleteMultipartUpload
    DELETE /bucket/key?uploadId=    AbortMultipartUpload
(rgw/rgw_op.cc RGWInitMultipart/RGWPutObj 'multipart'/
 RGWCompleteMultipart/RGWAbortMultipart, rgw_rest_s3.cc)
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, quote, unquote, urlparse
from xml.sax.saxutils import escape

from ..client.rados import RadosError
from ..client.striper import Layout, StripedObject
from ..utils import denc
from . import auth_v4

BUCKETS_ROOT = "rgw.buckets"        # omap: bucket name -> meta
DATA_POOL = "rgw_data"


def index_oid(bucket: str) -> str:
    return f"bucket.index.{bucket}"


def uploads_oid(bucket: str) -> str:
    """omap: uploadId -> {key, started} (RGWMPObj meta analog)."""
    return f"bucket.uploads.{quote(bucket, safe='')}"


def parts_oid(bucket: str, upload_id: str) -> str:
    """omap: zero-padded part number -> {etag, size}."""
    return f"bucket.parts.{quote(bucket, safe='')}.{upload_id}"


def part_soid(bucket: str, key: str, upload_id: str, n: int) -> str:
    return obj_soid(bucket, key) + f".mp.{upload_id}.{n:05d}"


def obj_soid(bucket: str, key: str) -> str:
    """Collision-proof backing name: bucket and key are fully quoted
    (so 'a'/'b.c' and 'a.b'/'c' cannot alias, and '@' — reserved by
    the OSD namespace — never appears) and joined with '/', which the
    quoting removes from both halves."""
    return f"obj.{quote(bucket, safe='')}/{quote(key, safe='')}"


def versions_oid(bucket: str) -> str:
    """omap: quoted-key + NUL + version-id -> version meta.  The vid
    is a descending time stamp (see new_version_id), so a ranged read
    under one key's prefix walks versions newest-first."""
    return f"bucket.versions.{quote(bucket, safe='')}"


def version_key(key: str, vid: str) -> str:
    return f"{quote(key, safe='')}\x00{vid}"


def ver_soid(bucket: str, key: str, vid: str) -> str:
    """Backing object for one version.  The 'null' version (pre-
    versioning writes, and writes while suspended) lives at the base
    name so enabling versioning needs no data movement."""
    base = obj_soid(bucket, key)
    return base if vid == "null" else f"{base}.v.{vid}"


def bilog_oid(bucket: str) -> str:
    """omap: zero-padded seq -> replication-log entry (the cls_rgw
    bucket-index log reduced; rgw_data_sync.h incremental-sync feed)."""
    return f"bucket.bilog.{quote(bucket, safe='')}"


def new_version_id() -> str:
    """Lexically ASCENDING = newest first (complemented nanoseconds),
    plus randomness against same-tick collisions."""
    import os
    return (f"{0xFFFFFFFFFFFFFFFF - time.time_ns():016x}"
            f"{os.urandom(3).hex()}")


class RGWDaemon:
    """The radosgw process: HTTP frontend over a Rados handle."""

    def __init__(self, rados, port: int = 0, access_key: str = "",
                 secret_key: str = "", data_pool: str = DATA_POOL):
        self.rados = rados
        self.access_key = access_key
        self.secret_key = secret_key
        try:
            rados.create_pool(data_pool)
        except RadosError:
            pass
        self.io = rados.open_ioctx(data_pool)
        # per-key mutation guard (cls_rgw's prepare/complete head
        # guard reduced): PUT is remove-then-write-then-index and
        # DELETE is remove-then-unindex, so two overlapping mutations
        # on one key could interleave into an index entry pointing at
        # removed data — a permanent tear no read retry can settle
        self._keylock_mu = threading.Lock()
        self._keylocks: dict[tuple, threading.Lock] = {}
        gw = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_GET(self):
                gw.handle(self, "GET")

            def do_PUT(self):
                gw.handle(self, "PUT")

            def do_DELETE(self):
                gw.handle(self, "DELETE")

            def do_HEAD(self):
                gw.handle(self, "HEAD")

            def do_POST(self):
                gw.handle(self, "POST")

        self.httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self.httpd.server_address[1]
        self._thread: threading.Thread | None = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "RGWDaemon":
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True, name="rgw-http")
        self._thread.start()
        return self

    def shutdown(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()

    # -- auth (AWS v2-style shared-key signatures) -------------------------

    def _check_auth(self, req, method: str, path: str,
                    raw_query: str = "", body: bytes = b"") -> bool:
        if not self.access_key:
            return True                      # auth disabled
        header = req.headers.get("Authorization", "")
        if header.startswith(auth_v4.ALGORITHM):
            headers = {k.lower(): v for k, v in req.headers.items()}
            return auth_v4.verify_v4(method, path, raw_query, headers,
                                     body, self.access_key,
                                     self.secret_key)
        want = sign_v2(method, path, req.headers.get("Date", ""),
                       self.access_key, self.secret_key)
        return hmac.compare_digest(want, header)

    # -- replication log (cls_rgw bilog reduced) ---------------------------

    def _bilog(self, bucket: str, op: str, key: str,
               vid: str | None = None) -> None:
        """Append one entry to the bucket's replication log.  Seq is
        allocated from a per-bucket counter key; readers page with
        ?bilog&marker=N (rgw_data_sync.h incremental feed)."""
        try:
            # one atomic in-OSD append: concurrent object ops cannot
            # collide on a seq or clobber each other's entries
            self.io.execute(bilog_oid(bucket), "kvstore",
                            "append_log", denc.dumps({
                                "entry": denc.dumps(
                                    {"op": op, "key": key, "vid": vid,
                                     "ts": _http_date()})}))
        except RadosError:
            pass          # replication log must never fail the op

    def _bilog_page(self, bucket: str, marker: int,
                    count: int = 1000) -> list[dict]:
        try:
            vals = self.io.get_omap_vals(
                bilog_oid(bucket), start_after=f"{marker:020d}",
                prefix="", max_return=count + 1)
        except RadosError:
            return []
        out = []
        for k in sorted(vals):
            if k.startswith("\x00"):
                continue
            ent = denc.loads(vals[k])
            ent["seq"] = int(k)
            out.append(ent)
        return out[:count]

    def _create_bucket(self, bucket: str) -> None:
        self._set_bucket_meta(bucket, {"created": _http_date()})
        try:
            self.io.write_full(index_oid(bucket), b"")
        except RadosError:
            pass

    def _remove_bucket(self, bucket: str) -> None:
        self.io.rm_omap_keys(BUCKETS_ROOT, [bucket])
        for oid in (index_oid(bucket), bilog_oid(bucket)):
            try:
                self.io.remove_object(oid)
            except RadosError:
                pass

    # -- bucket metadata ---------------------------------------------------

    def _buckets(self) -> dict:
        try:
            return {k: denc.loads(v)
                    for k, v in self.io.get_omap(BUCKETS_ROOT).items()}
        except RadosError:
            return {}

    def _bucket_exists(self, bucket: str) -> bool:
        return self._bucket_meta(bucket) is not None

    def _bucket_meta(self, bucket: str) -> dict | None:
        try:
            got = self.io.get_omap_keys(BUCKETS_ROOT, [bucket])
        except RadosError:
            return None
        blob = got.get(bucket)
        return denc.loads(blob) if blob else None

    def _set_bucket_meta(self, bucket: str, meta: dict) -> None:
        self.io.set_omap(BUCKETS_ROOT, {bucket: denc.dumps(meta)})

    def _index_entry(self, bucket: str, key: str) -> dict | None:
        """One key's index record — a single-key omap read, not the
        whole bucket index."""
        try:
            got = self.io.get_omap_keys(index_oid(bucket), [key])
        except RadosError:
            return None
        blob = got.get(key)
        return denc.loads(blob) if blob else None

    def _index_page(self, bucket: str, marker: str, prefix: str,
                    count: int) -> dict:
        try:
            return {k: denc.loads(v) for k, v in self.io.get_omap_vals(
                index_oid(bucket), start_after=marker, prefix=prefix,
                max_return=count).items()}
        except RadosError:
            return {}

    def _index_empty(self, bucket: str) -> bool:
        return not self._index_page(bucket, "", "", 1)

    # -- request routing ---------------------------------------------------

    def handle(self, req, method: str) -> None:
        parsed = urlparse(req.path)
        path = unquote(parsed.path)
        query = parse_qs(parsed.query, keep_blank_values=True)
        # drain the request body FIRST: replying on an error path with
        # unread body bytes desyncs the keep-alive connection (the next
        # request line would be parsed out of the leftover payload)
        try:
            length = int(req.headers.get("Content-Length", 0) or 0)
        except ValueError:
            self._error(req, 400, "InvalidArgument")
            return
        body = req.rfile.read(length) if length > 0 else b""
        from . import swift
        authz = req.headers.get("Authorization", "")
        if swift.handles(path) and not authz.startswith("AWS"):
            # the Swift dialect authenticates with its own TempAuth
            # token (rgw_rest_swift.cc), not AWS signatures
            try:
                swift.dispatch(self, req, method, path, query, body)
            except RadosError as e:
                self._error(req, 500, f"InternalError: {e}")
            return
        if not self._check_auth(req, method, path, parsed.query, body):
            self._error(req, 403, "AccessDenied")
            return
        parts = [p for p in path.split("/") if p]
        try:
            if not parts:
                if method == "GET":
                    self._list_buckets(req)
                else:
                    self._error(req, 405, "MethodNotAllowed")
            elif len(parts) == 1:
                self._bucket_op(req, method, parts[0], query, body)
            else:
                self._object_op(req, method, parts[0],
                                "/".join(parts[1:]), body, query)
        except RadosError as e:
            self._error(req, 500, f"InternalError: {e}")

    # -- responses ---------------------------------------------------------

    def _reply(self, req, code: int, body: bytes = b"",
               headers: dict | None = None) -> None:
        req.send_response(code)
        have_len = False
        for k, v in (headers or {}).items():
            req.send_header(k, v)
            if k.lower() == "content-length":
                have_len = True      # HEAD advertises the entity size
        if not have_len:
            req.send_header("Content-Length", str(len(body)))
        req.end_headers()
        if req.command != "HEAD" and len(body):
            # gather-write: striper reads arrive as BufferList ropes —
            # the segments go straight to the socket, never joined
            from ..utils.bufferlist import iov_of
            for seg in iov_of(body):
                req.wfile.write(seg)

    def _xml(self, req, code: int, body: str,
             headers: dict | None = None) -> None:
        self._reply(req, code,
                    ('<?xml version="1.0" encoding="UTF-8"?>'
                     + body).encode(),
                    {"Content-Type": "application/xml",
                     **(headers or {})})

    def _error(self, req, code: int, s3code: str) -> None:
        self._xml(req, code, f"<Error><Code>{escape(s3code)}</Code>"
                             f"</Error>")

    # -- bucket ops --------------------------------------------------------

    def _list_buckets(self, req) -> None:
        entries = "".join(
            f"<Bucket><Name>{escape(name)}</Name>"
            f"<CreationDate>{meta['created']}</CreationDate></Bucket>"
            for name, meta in sorted(self._buckets().items()))
        self._xml(req, 200,
                  "<ListAllMyBucketsResult><Buckets>"
                  f"{entries}</Buckets></ListAllMyBucketsResult>")

    def _bucket_op(self, req, method: str, bucket: str,
                   query: dict, body: bytes = b"") -> None:
        if "versioning" in query:
            self._versioning_op(req, method, bucket, body)
            return
        if "versions" in query and method in ("GET", "HEAD"):
            self._list_versions(req, bucket, query)
            return
        if "bilog" in query and method == "GET":
            import json
            try:
                marker = int(query.get("marker", ["0"])[0])
            except ValueError:
                self._error(req, 400, "InvalidArgument")
                return
            entries = self._bilog_page(bucket, marker)
            self._reply(req, 200, json.dumps(entries).encode(),
                        {"Content-Type": "application/json"})
            return
        buckets = self._buckets()
        if method == "PUT":
            if bucket in buckets:
                self._error(req, 409, "BucketAlreadyExists")
                return
            self._create_bucket(bucket)
            self._reply(req, 200)
        elif method == "DELETE":
            if bucket not in buckets:
                self._error(req, 404, "NoSuchBucket")
                return
            if not self._index_empty(bucket):
                self._error(req, 409, "BucketNotEmpty")
                return
            self._remove_bucket(bucket)
            self._reply(req, 204)
        elif method in ("GET", "HEAD"):
            if bucket not in buckets:
                self._error(req, 404, "NoSuchBucket")
                return
            if "uploads" in query:
                self._list_uploads(req, bucket)
                return
            prefix = query.get("prefix", [""])[0]
            marker = query.get("marker", [""])[0]
            try:
                max_keys = int(query.get("max-keys", ["1000"])[0])
            except ValueError:
                self._error(req, 400, "InvalidArgument")
                return
            if max_keys < 0:
                self._error(req, 400, "InvalidArgument")
                return
            # ranged index read: one page + 1 sentinel for IsTruncated
            # (RGWRados::cls_bucket_list marker pagination)
            # delete-marker-latest keys are invisible to a plain list
            # (RGWListBucket skips entries whose current version is a
            # marker); page through the index until a full page of
            # visible keys (or exhaustion)
            page = {}
            cursor = marker
            exhausted = False
            while len(page) <= max_keys and not exhausted:
                chunk = self._index_page(bucket, cursor, prefix,
                                         max_keys + 1)
                if len(chunk) < max_keys + 1:
                    exhausted = True
                for k, v in chunk.items():
                    if not v.get("delete_marker"):
                        page[k] = v
                if chunk:
                    cursor = max(chunk)
            keys = sorted(page)
            truncated = len(keys) > max_keys
            keys = keys[:max_keys]
            entries = "".join(
                f"<Contents><Key>{escape(k)}</Key>"
                f"<Size>{page[k]['size']}</Size>"
                f"<ETag>&quot;{page[k]['etag']}&quot;</ETag>"
                "</Contents>"
                for k in keys)
            next_marker = (f"<NextMarker>{escape(keys[-1])}"
                           f"</NextMarker>") if truncated and keys \
                else ""
            self._xml(req, 200,
                      "<ListBucketResult>"
                      f"<Name>{escape(bucket)}</Name>"
                      f"<Prefix>{escape(prefix)}</Prefix>"
                      f"<Marker>{escape(marker)}</Marker>"
                      f"<KeyCount>{len(keys)}</KeyCount>"
                      f"<IsTruncated>{str(truncated).lower()}"
                      f"</IsTruncated>{next_marker}{entries}"
                      "</ListBucketResult>")
        else:
            self._error(req, 405, "MethodNotAllowed")

    # -- versioning (rgw/rgw_op.h:484-493 RGWGet/SetBucketVersioning) ------

    def _versioning_op(self, req, method: str, bucket: str,
                       body: bytes) -> None:
        meta = self._bucket_meta(bucket)
        if meta is None:
            self._error(req, 404, "NoSuchBucket")
            return
        if method in ("GET", "HEAD"):
            status = meta.get("versioning", "")
            inner = f"<Status>{status}</Status>" if status else ""
            self._xml(req, 200,
                      '<VersioningConfiguration xmlns="http://s3.'
                      f'amazonaws.com/doc/2006-03-01/">{inner}'
                      "</VersioningConfiguration>")
        elif method == "PUT":
            import re
            m = re.search(rb"<Status>\s*(Enabled|Suspended)\s*"
                          rb"</Status>", body)
            if m is None:
                self._error(req, 400, "IllegalVersioningConfiguration"
                                      "Exception")
                return
            meta["versioning"] = m.group(1).decode()
            self._set_bucket_meta(bucket, meta)
            self._reply(req, 200)
        else:
            self._error(req, 405, "MethodNotAllowed")

    def _version_record(self, bucket: str, key: str,
                        vid: str) -> dict | None:
        try:
            got = self.io.get_omap_keys(versions_oid(bucket),
                                        [version_key(key, vid)])
        except RadosError:
            got = {}          # no versions object yet: still fall
                              # through to the null-version fallback
        blob = got.get(version_key(key, vid))
        if blob:
            return denc.loads(blob)
        if vid == "null":
            # a pre-versioning object is addressable as version "null"
            # IMMEDIATELY (S3 null-version semantics); the omap record
            # only materializes on the next write (_migrate_null_
            # version), so fall back to the unmigrated index entry
            ent = self._index_entry(bucket, key)
            if ent is not None and \
                    ent.get("version_id", "null") == "null":
                return ent
        return None

    def _put_version_record(self, bucket: str, key: str, vid: str,
                            rec: dict) -> None:
        self.io.set_omap(versions_oid(bucket),
                         {version_key(key, vid): denc.dumps(rec)})

    def _key_versions(self, bucket: str, key: str) -> list[tuple]:
        """All (vid, record) for one key, newest first (vids are
        complemented timestamps, so lexical order IS newest-first)."""
        prefix = quote(key, safe="") + "\x00"
        try:
            vals = self.io.get_omap_vals(versions_oid(bucket),
                                         start_after="", prefix=prefix,
                                         max_return=100000)
        except RadosError:
            return []
        out = [(k[len(prefix):], denc.loads(v))
               for k, v in sorted(vals.items())]
        # a "null" vid sorts after hex stamps; order by recorded mtime
        out.sort(key=lambda t: -t[1].get("mtime_ns", 0))
        return out

    def _migrate_null_version(self, bucket: str, key: str) -> None:
        """First versioned write over a pre-versioning object: the
        existing base-name data becomes the 'null' version (S3's
        null-version semantics — no data movement, just a record)."""
        ent = self._index_entry(bucket, key)
        if ent is not None and "version_id" not in ent:
            ent["version_id"] = "null"
            ent["mtime_ns"] = ent.get("mtime_ns", 0)
            self._put_version_record(bucket, key, "null", ent)

    def _list_versions(self, req, bucket: str, query: dict) -> None:
        if not self._bucket_exists(bucket):
            self._error(req, 404, "NoSuchBucket")
            return
        prefix = query.get("prefix", [""])[0]
        try:
            vals = self.io.get_omap_vals(
                versions_oid(bucket), start_after="",
                prefix=quote(prefix, safe="") if prefix else "",
                max_return=100000)
        except RadosError:
            vals = {}
        per_key: dict[str, list] = {}
        for k, blob in vals.items():
            qkey, _, vid = k.partition("\x00")
            per_key.setdefault(unquote(qkey), []).append(
                (vid, denc.loads(blob)))
        entries = []
        for key in sorted(per_key):
            cur = self._index_entry(bucket, key) or {}
            latest_vid = cur.get("version_id")
            vers = sorted(per_key[key],
                          key=lambda t: -t[1].get("mtime_ns", 0))
            for vid, rec in vers:
                tag = ("DeleteMarker" if rec.get("delete_marker")
                       else "Version")
                extra = ("" if rec.get("delete_marker") else
                         f"<Size>{rec.get('size', 0)}</Size>"
                         f"<ETag>&quot;{rec.get('etag', '')}&quot;"
                         "</ETag>")
                entries.append(
                    f"<{tag}><Key>{escape(key)}</Key>"
                    f"<VersionId>{vid}</VersionId>"
                    f"<IsLatest>{str(vid == latest_vid).lower()}"
                    f"</IsLatest>"
                    f"<LastModified>{rec.get('mtime', '')}"
                    f"</LastModified>{extra}</{tag}>")
        self._xml(req, 200,
                  "<ListVersionsResult>"
                  f"<Name>{escape(bucket)}</Name>"
                  f"<Prefix>{escape(prefix)}</Prefix>"
                  f"{''.join(entries)}</ListVersionsResult>")

    # -- object ops --------------------------------------------------------

    def _object_op(self, req, method: str, bucket: str,
                   key: str, body: bytes = b"",
                   query: dict | None = None) -> None:
        query = query or {}
        bmeta = self._bucket_meta(bucket)
        if bmeta is None:
            self._error(req, 404, "NoSuchBucket")
            return
        vstate = bmeta.get("versioning", "")
        upload_id = query.get("uploadId", [None])[0]
        if method == "POST" and "uploads" in query:
            self._initiate_multipart(req, bucket, key)
            return
        if upload_id is not None:
            if method == "PUT":
                self._upload_part(req, bucket, key, upload_id,
                                  query, body)
            elif method == "POST":
                self._complete_multipart(req, bucket, key, upload_id,
                                         body)
            elif method == "DELETE":
                self._abort_multipart(req, bucket, key, upload_id)
            else:
                self._error(req, 405, "MethodNotAllowed")
            return
        req_vid = query.get("versionId", [None])[0]
        if method == "PUT":
            self._put_object(req, bucket, key, body, vstate)
        elif method in ("GET", "HEAD"):
            self._get_object(req, method, bucket, key, req_vid)
        elif method == "DELETE":
            self._delete_object(req, bucket, key, req_vid, vstate)
        else:
            self._error(req, 405, "MethodNotAllowed")

    @staticmethod
    def _serve_tag_ok(ent: dict, data: bytes) -> bool:
        """True when the bytes about to be served match the index
        entry that advertised them.  The etag is the exact tag for a
        plain PUT (md5 of the body); a striper read racing a
        remove-then-write returns sparse ZEROS of the right length,
        which only the content hash catches.  Multipart etags are
        compound (md5-of-md5s ``-N``), so those fall back to the
        length check."""
        if len(data) != int(ent["size"]):
            return False
        etag = ent.get("etag", "")
        if "-" in etag:
            return True
        from ..utils.bufferlist import iov_of
        m = hashlib.md5()
        for seg in iov_of(data):
            m.update(seg)
        return m.hexdigest() == etag

    def _keylock(self, bucket: str, key: str) -> threading.Lock:
        with self._keylock_mu:
            return self._keylocks.setdefault((bucket, key),
                                             threading.Lock())

    def _put_object(self, req, bucket: str, key: str, body: bytes,
                    vstate: str, swift_status: int | None = None) -> None:
        with self._keylock(bucket, key):
            self._put_object_locked(req, bucket, key, body, vstate,
                                    swift_status)

    def _put_object_locked(self, req, bucket: str, key: str,
                           body: bytes, vstate: str,
                           swift_status: int | None = None) -> None:
        etag = hashlib.md5(body).hexdigest()
        ent = {"size": len(body), "etag": etag, "mtime": _http_date(),
               "mtime_ns": time.time_ns()}
        headers = {"ETag": f'"{etag}"'}
        if vstate == "Enabled":
            self._migrate_null_version(bucket, key)
            vid = new_version_id()
            ent["version_id"] = vid
            StripedObject(self.io, ver_soid(bucket, key, vid)).write(
                body)
            self._put_version_record(bucket, key, vid, ent)
            headers["x-amz-version-id"] = vid
        else:
            # unversioned OR suspended: (over)write the null version.
            # Always clear the base object first — StripedObject.write
            # never truncates, so writing a shorter body over leftover
            # base data would serve a stale tail
            so = StripedObject(self.io, obj_soid(bucket, key))
            try:
                so.remove()
            except RadosError:
                pass
            so.write(body)
            if vstate == "Suspended":
                ent["version_id"] = "null"
                self._put_version_record(bucket, key, "null", ent)
                headers["x-amz-version-id"] = "null"
        self.io.set_omap(index_oid(bucket), {key: denc.dumps(ent)})
        self._bilog(bucket, "put", key, ent.get("version_id"))
        self._reply(req, swift_status or 200, headers=headers)

    def _get_object(self, req, method: str, bucket: str, key: str,
                    req_vid: str | None) -> None:
        # torn-read retry (RGWRados::get_obj's -ECANCELED loop): the
        # unversioned PUT path is remove-then-write (the striper never
        # truncates) and DELETE is remove-then-unindex, so a GET
        # landing inside either window can pair a live index entry
        # with missing/partial data.  Real RGW detects the head tag
        # changing under the read and restarts; here the index entry's
        # recorded size is the tag — on mismatch re-read from the
        # index, and only a persistent tear (never observed outside a
        # true race) surfaces as a retryable 500
        for _ in range(20):
            if req_vid is None:
                ent = self._index_entry(bucket, key)
                if ent is None:
                    self._error(req, 404, "NoSuchKey")
                    return
                if ent.get("delete_marker"):
                    req.send_response(404)
                    req.send_header("x-amz-delete-marker", "true")
                    req.send_header("x-amz-version-id",
                                    ent.get("version_id", "null"))
                    req.send_header("Content-Length", "0")
                    req.end_headers()
                    return
                vid = ent.get("version_id", "null")
            else:
                vid = req_vid
                ent = self._version_record(bucket, key, vid)
                if ent is None:
                    self._error(req, 404, "NoSuchVersion")
                    return
                if ent.get("delete_marker"):
                    # GET on a delete-marker version is 405 per S3
                    self._error(req, 405, "MethodNotAllowed")
                    return
            so = StripedObject(self.io, ver_soid(bucket, key, vid))
            data = so.read() if method == "GET" else b""
            if method != "GET" or self._serve_tag_ok(ent, data):
                break
            time.sleep(0.05)
        else:
            self._error(req, 500, "ReadRaceNotSettled")
            return
        req.send_response(200)
        # GET: length of what we actually send (a concurrent
        # overwrite can race the index read); HEAD: index size
        req.send_header("Content-Length",
                        str(len(data)) if method == "GET"
                        else str(ent["size"]))
        req.send_header("ETag", f'"{ent["etag"]}"')
        req.send_header("Last-Modified", ent["mtime"])
        if vid != "null" or req_vid is not None:
            req.send_header("x-amz-version-id", vid)
        req.send_header("Content-Type", "application/octet-stream")
        req.end_headers()
        if method == "GET":
            from ..utils.bufferlist import iov_of
            for seg in iov_of(data):
                req.wfile.write(seg)

    def _delete_object(self, req, bucket: str, key: str,
                       req_vid: str | None, vstate: str) -> None:
        with self._keylock(bucket, key):
            self._delete_object_locked(req, bucket, key, req_vid,
                                       vstate)

    def _delete_object_locked(self, req, bucket: str, key: str,
                              req_vid: str | None, vstate: str) -> None:
        if req_vid is not None:
            self._delete_version(req, bucket, key, req_vid)
            return
        if vstate in ("Enabled", "Suspended"):
            # plant a delete marker (RGWDeleteObj's versioned path);
            # suspended buckets use the null id, replacing any null
            # version outright
            self._migrate_null_version(bucket, key)
            vid = (new_version_id() if vstate == "Enabled" else "null")
            if vid == "null":
                old = self._version_record(bucket, key, "null")
                if old is not None and not old.get("delete_marker"):
                    StripedObject(self.io,
                                  ver_soid(bucket, key, "null")).remove()
            marker = {"delete_marker": True, "version_id": vid,
                      "mtime": _http_date(), "mtime_ns": time.time_ns()}
            self._put_version_record(bucket, key, vid, marker)
            self.io.set_omap(index_oid(bucket),
                             {key: denc.dumps(marker)})
            self._bilog(bucket, "delete-marker", key, vid)
            self._reply(req, 204, headers={
                "x-amz-delete-marker": "true",
                "x-amz-version-id": vid})
            return
        if self._index_entry(bucket, key) is not None:
            StripedObject(self.io, obj_soid(bucket, key)).remove()
            self.io.rm_omap_keys(index_oid(bucket), [key])
            self._bilog(bucket, "delete", key)
        self._reply(req, 204)

    def _delete_version(self, req, bucket: str, key: str,
                        vid: str) -> None:
        """Permanent removal of one version; deleting the current
        delete marker restores the previous version as latest."""
        rec = self._version_record(bucket, key, vid)
        if rec is None:
            self._error(req, 404, "NoSuchVersion")
            return
        if not rec.get("delete_marker"):
            try:
                StripedObject(self.io,
                              ver_soid(bucket, key, vid)).remove()
            except RadosError:
                pass
        self.io.rm_omap_keys(versions_oid(bucket),
                             [version_key(key, vid)])
        cur = self._index_entry(bucket, key)
        if cur is not None and cur.get("version_id", "null") == vid:
            remaining = self._key_versions(bucket, key)
            if remaining:
                _, newest = remaining[0]
                self.io.set_omap(index_oid(bucket),
                                 {key: denc.dumps(newest)})
            else:
                self.io.rm_omap_keys(index_oid(bucket), [key])
        self._bilog(bucket, "delete-version", key, vid)
        headers = {"x-amz-version-id": vid}
        if rec.get("delete_marker"):
            headers["x-amz-delete-marker"] = "true"
        self._reply(req, 204, headers=headers)

    # -- multipart upload (RGWInitMultipart/RGWCompleteMultipart) ----------

    def _initiate_multipart(self, req, bucket: str, key: str) -> None:
        import uuid
        upload_id = uuid.uuid4().hex[:16]
        self.io.set_omap(uploads_oid(bucket), {upload_id: denc.dumps(
            {"key": key, "started": _http_date()})})
        self._xml(req, 200,
                  "<InitiateMultipartUploadResult>"
                  f"<Bucket>{escape(bucket)}</Bucket>"
                  f"<Key>{escape(key)}</Key>"
                  f"<UploadId>{upload_id}</UploadId>"
                  "</InitiateMultipartUploadResult>")

    def _upload_meta(self, bucket: str, upload_id: str) -> dict | None:
        try:
            got = self.io.get_omap_keys(uploads_oid(bucket),
                                        [upload_id])
        except RadosError:
            return None
        blob = got.get(upload_id)
        return denc.loads(blob) if blob else None

    def _upload_part(self, req, bucket: str, key: str, upload_id: str,
                     query: dict, body: bytes) -> None:
        meta = self._upload_meta(bucket, upload_id)
        if meta is None or meta["key"] != key:
            self._error(req, 404, "NoSuchUpload")
            return
        try:
            n = int(query.get("partNumber", ["0"])[0])
        except ValueError:
            n = 0
        if not 1 <= n <= 10000:
            self._error(req, 400, "InvalidPartNumber")
            return
        StripedObject(self.io,
                      part_soid(bucket, key, upload_id, n)).write(body)
        etag = hashlib.md5(body).hexdigest()
        self.io.set_omap(parts_oid(bucket, upload_id), {
            f"{n:05d}": denc.dumps({"etag": etag,
                                    "size": len(body)})})
        self._reply(req, 200, headers={"ETag": f'"{etag}"'})

    def _complete_multipart(self, req, bucket: str, key: str,
                            upload_id: str, body: bytes) -> None:
        import re
        meta = self._upload_meta(bucket, upload_id)
        if meta is None or meta["key"] != key:
            self._error(req, 404, "NoSuchUpload")
            return
        try:
            parts = {int(k): denc.loads(v) for k, v in
                     self.io.get_omap(parts_oid(bucket,
                                                upload_id)).items()}
        except RadosError:
            parts = {}
        want = [int(m) for m in
                re.findall(r"<PartNumber>(\d+)</PartNumber>",
                           body.decode("utf-8", "replace"))] \
            if body else sorted(parts)
        if not want or any(n not in parts for n in want):
            self._error(req, 400, "InvalidPart")
            return
        if any(b <= a for a, b in zip(want, want[1:])):
            # S3 requires strictly ascending part numbers — which also
            # rejects duplicates (a part listed twice would be
            # concatenated twice into the final object)
            self._error(req, 400, "InvalidPartOrder")
            return
        # assemble: copy each part into the final object at its
        # cumulative offset (RGWCompleteMultipart assembles via the
        # manifest; here data moves once through the striper).  On a
        # versioning-enabled bucket the completed object is a NEW
        # version, like any other PUT.
        bmeta = self._bucket_meta(bucket) or {}
        vstate = bmeta.get("versioning", "")
        vid = None
        if vstate == "Enabled":
            self._migrate_null_version(bucket, key)
            vid = new_version_id()
            final = StripedObject(self.io, ver_soid(bucket, key, vid))
        else:
            if vstate == "Suspended":
                vid = "null"
            final = StripedObject(self.io, obj_soid(bucket, key))
            try:
                final.remove()   # write never truncates: clear first
            except RadosError:
                pass
        offset = 0
        md5s = []
        for n in want:
            data = StripedObject(
                self.io, part_soid(bucket, key, upload_id, n)).read()
            final.write(data, offset=offset)
            offset += len(data)
            from ..utils.bufferlist import iov_of
            m = hashlib.md5()
            for seg in iov_of(data):
                m.update(seg)
            md5s.append(m.digest())
        etag = hashlib.md5(b"".join(md5s)).hexdigest() + \
            f"-{len(want)}"
        ent = {"size": offset, "etag": etag, "mtime": _http_date(),
               "mtime_ns": time.time_ns()}
        if vid is not None:
            ent["version_id"] = vid
            self._put_version_record(bucket, key, vid, ent)
        self.io.set_omap(index_oid(bucket), {key: denc.dumps(ent)})
        self._bilog(bucket, "put", key, vid)
        self._cleanup_upload(bucket, key, upload_id, parts)
        self._xml(req, 200,
                  "<CompleteMultipartUploadResult>"
                  f"<Bucket>{escape(bucket)}</Bucket>"
                  f"<Key>{escape(key)}</Key>"
                  f"<ETag>&quot;{etag}&quot;</ETag>"
                  "</CompleteMultipartUploadResult>",
                  headers={"x-amz-version-id": vid} if vid else None)

    def _abort_multipart(self, req, bucket: str, key: str,
                         upload_id: str) -> None:
        meta = self._upload_meta(bucket, upload_id)
        if meta is None:
            self._error(req, 404, "NoSuchUpload")
            return
        try:
            parts = {int(k): denc.loads(v) for k, v in
                     self.io.get_omap(parts_oid(bucket,
                                                upload_id)).items()}
        except RadosError:
            parts = {}
        self._cleanup_upload(bucket, meta["key"], upload_id, parts)
        self._reply(req, 204)

    def _cleanup_upload(self, bucket: str, key: str, upload_id: str,
                        parts: dict) -> None:
        for n in parts:
            try:
                StripedObject(self.io, part_soid(bucket, key,
                                                 upload_id, n)).remove()
            except RadosError:
                pass
        try:
            self.io.remove_object(parts_oid(bucket, upload_id))
        except RadosError:
            pass
        try:
            self.io.rm_omap_keys(uploads_oid(bucket), [upload_id])
        except RadosError:
            pass

    def _list_uploads(self, req, bucket: str) -> None:
        try:
            ups = {k: denc.loads(v) for k, v in
                   self.io.get_omap(uploads_oid(bucket)).items()}
        except RadosError:
            ups = {}
        entries = "".join(
            f"<Upload><Key>{escape(m['key'])}</Key>"
            f"<UploadId>{uid}</UploadId>"
            f"<Initiated>{m['started']}</Initiated></Upload>"
            for uid, m in sorted(ups.items()))
        self._xml(req, 200,
                  "<ListMultipartUploadsResult>"
                  f"<Bucket>{escape(bucket)}</Bucket>{entries}"
                  "</ListMultipartUploadsResult>")


def _http_date() -> str:
    return time.strftime("%a, %d %b %Y %H:%M:%S GMT", time.gmtime())


def sign_v2(method: str, path: str, date: str, access: str,
            secret: str) -> str:
    """Client-side helper producing the Authorization header."""
    to_sign = "\n".join([method, "", "", date, path])
    sig = base64.b64encode(hmac.new(
        secret.encode(), to_sign.encode(), hashlib.sha1).digest()
    ).decode()
    return f"AWS {access}:{sig}"
