"""RGW: S3-dialect HTTP object gateway (rgw/rgw_main.cc, rgw_rest_s3.cc
reduced to the core object workflow).

The reference fronts RADOS with civetweb/asio frontends, a REST dialect
layer, and cls_rgw-maintained bucket indexes.  This gateway keeps that
shape: a threaded stdlib HTTP frontend, bucket metadata + per-bucket
indexes in omaps (mutated server-side), object data striped into the
data pool, and optional AWS-v2-style signature auth.  Multisite sync,
lifecycle, versioning and the Swift dialect are out of scope.

S3 surface:
    GET  /                          ListAllMyBuckets
    PUT  /bucket                    create bucket
    DELETE /bucket                  delete (must be empty)
    GET  /bucket?prefix=&max-keys=&marker=   ListBucket (paginated:
                                    NextMarker continuation, index read
                                    via ranged omap — O(page), not
                                    O(bucket))
    GET  /bucket?uploads            list in-progress multipart uploads
    PUT  /bucket/key                put object
    GET|HEAD /bucket/key            get/stat object
    DELETE /bucket/key              delete object
    POST /bucket/key?uploads        InitiateMultipartUpload
    PUT  /bucket/key?uploadId=&partNumber=   UploadPart
    POST /bucket/key?uploadId=      CompleteMultipartUpload
    DELETE /bucket/key?uploadId=    AbortMultipartUpload
(rgw/rgw_op.cc RGWInitMultipart/RGWPutObj 'multipart'/
 RGWCompleteMultipart/RGWAbortMultipart, rgw_rest_s3.cc)
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, quote, unquote, urlparse
from xml.sax.saxutils import escape

from ..client.rados import RadosError
from ..client.striper import Layout, StripedObject
from ..utils import denc

BUCKETS_ROOT = "rgw.buckets"        # omap: bucket name -> meta
DATA_POOL = "rgw_data"


def index_oid(bucket: str) -> str:
    return f"bucket.index.{bucket}"


def uploads_oid(bucket: str) -> str:
    """omap: uploadId -> {key, started} (RGWMPObj meta analog)."""
    return f"bucket.uploads.{quote(bucket, safe='')}"


def parts_oid(bucket: str, upload_id: str) -> str:
    """omap: zero-padded part number -> {etag, size}."""
    return f"bucket.parts.{quote(bucket, safe='')}.{upload_id}"


def part_soid(bucket: str, key: str, upload_id: str, n: int) -> str:
    return obj_soid(bucket, key) + f".mp.{upload_id}.{n:05d}"


def obj_soid(bucket: str, key: str) -> str:
    """Collision-proof backing name: bucket and key are fully quoted
    (so 'a'/'b.c' and 'a.b'/'c' cannot alias, and '@' — reserved by
    the OSD namespace — never appears) and joined with '/', which the
    quoting removes from both halves."""
    return f"obj.{quote(bucket, safe='')}/{quote(key, safe='')}"


class RGWDaemon:
    """The radosgw process: HTTP frontend over a Rados handle."""

    def __init__(self, rados, port: int = 0, access_key: str = "",
                 secret_key: str = "", data_pool: str = DATA_POOL):
        self.rados = rados
        self.access_key = access_key
        self.secret_key = secret_key
        try:
            rados.create_pool(data_pool)
        except RadosError:
            pass
        self.io = rados.open_ioctx(data_pool)
        gw = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_GET(self):
                gw.handle(self, "GET")

            def do_PUT(self):
                gw.handle(self, "PUT")

            def do_DELETE(self):
                gw.handle(self, "DELETE")

            def do_HEAD(self):
                gw.handle(self, "HEAD")

            def do_POST(self):
                gw.handle(self, "POST")

        self.httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self.httpd.server_address[1]
        self._thread: threading.Thread | None = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "RGWDaemon":
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True, name="rgw-http")
        self._thread.start()
        return self

    def shutdown(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()

    # -- auth (AWS v2-style shared-key signatures) -------------------------

    def _check_auth(self, req, method: str, path: str) -> bool:
        if not self.access_key:
            return True                      # auth disabled
        header = req.headers.get("Authorization", "")
        want = sign_v2(method, path, req.headers.get("Date", ""),
                       self.access_key, self.secret_key)
        return hmac.compare_digest(want, header)

    # -- bucket metadata ---------------------------------------------------

    def _buckets(self) -> dict:
        try:
            return {k: denc.loads(v)
                    for k, v in self.io.get_omap(BUCKETS_ROOT).items()}
        except RadosError:
            return {}

    def _bucket_exists(self, bucket: str) -> bool:
        try:
            return bucket in self.io.get_omap_keys(BUCKETS_ROOT,
                                                   [bucket])
        except RadosError:
            return False

    def _index_entry(self, bucket: str, key: str) -> dict | None:
        """One key's index record — a single-key omap read, not the
        whole bucket index."""
        try:
            got = self.io.get_omap_keys(index_oid(bucket), [key])
        except RadosError:
            return None
        blob = got.get(key)
        return denc.loads(blob) if blob else None

    def _index_page(self, bucket: str, marker: str, prefix: str,
                    count: int) -> dict:
        try:
            return {k: denc.loads(v) for k, v in self.io.get_omap_vals(
                index_oid(bucket), start_after=marker, prefix=prefix,
                max_return=count).items()}
        except RadosError:
            return {}

    def _index_empty(self, bucket: str) -> bool:
        return not self._index_page(bucket, "", "", 1)

    # -- request routing ---------------------------------------------------

    def handle(self, req, method: str) -> None:
        parsed = urlparse(req.path)
        path = unquote(parsed.path)
        query = parse_qs(parsed.query, keep_blank_values=True)
        # drain the request body FIRST: replying on an error path with
        # unread body bytes desyncs the keep-alive connection (the next
        # request line would be parsed out of the leftover payload)
        try:
            length = int(req.headers.get("Content-Length", 0) or 0)
        except ValueError:
            self._error(req, 400, "InvalidArgument")
            return
        body = req.rfile.read(length) if length > 0 else b""
        if not self._check_auth(req, method, path):
            self._error(req, 403, "AccessDenied")
            return
        parts = [p for p in path.split("/") if p]
        try:
            if not parts:
                if method == "GET":
                    self._list_buckets(req)
                else:
                    self._error(req, 405, "MethodNotAllowed")
            elif len(parts) == 1:
                self._bucket_op(req, method, parts[0], query)
            else:
                self._object_op(req, method, parts[0],
                                "/".join(parts[1:]), body, query)
        except RadosError as e:
            self._error(req, 500, f"InternalError: {e}")

    # -- responses ---------------------------------------------------------

    def _reply(self, req, code: int, body: bytes = b"",
               headers: dict | None = None) -> None:
        req.send_response(code)
        for k, v in (headers or {}).items():
            req.send_header(k, v)
        req.send_header("Content-Length", str(len(body)))
        req.end_headers()
        if req.command != "HEAD" and body:
            req.wfile.write(body)

    def _xml(self, req, code: int, body: str) -> None:
        self._reply(req, code,
                    ('<?xml version="1.0" encoding="UTF-8"?>'
                     + body).encode(),
                    {"Content-Type": "application/xml"})

    def _error(self, req, code: int, s3code: str) -> None:
        self._xml(req, code, f"<Error><Code>{escape(s3code)}</Code>"
                             f"</Error>")

    # -- bucket ops --------------------------------------------------------

    def _list_buckets(self, req) -> None:
        entries = "".join(
            f"<Bucket><Name>{escape(name)}</Name>"
            f"<CreationDate>{meta['created']}</CreationDate></Bucket>"
            for name, meta in sorted(self._buckets().items()))
        self._xml(req, 200,
                  "<ListAllMyBucketsResult><Buckets>"
                  f"{entries}</Buckets></ListAllMyBucketsResult>")

    def _bucket_op(self, req, method: str, bucket: str,
                   query: dict) -> None:
        buckets = self._buckets()
        if method == "PUT":
            if bucket in buckets:
                self._error(req, 409, "BucketAlreadyExists")
                return
            self.io.set_omap(BUCKETS_ROOT, {bucket: denc.dumps(
                {"created": _http_date()})})
            self.io.write_full(index_oid(bucket), b"")
            self._reply(req, 200)
        elif method == "DELETE":
            if bucket not in buckets:
                self._error(req, 404, "NoSuchBucket")
                return
            if not self._index_empty(bucket):
                self._error(req, 409, "BucketNotEmpty")
                return
            self.io.rm_omap_keys(BUCKETS_ROOT, [bucket])
            try:
                self.io.remove_object(index_oid(bucket))
            except RadosError:
                pass
            self._reply(req, 204)
        elif method in ("GET", "HEAD"):
            if bucket not in buckets:
                self._error(req, 404, "NoSuchBucket")
                return
            if "uploads" in query:
                self._list_uploads(req, bucket)
                return
            prefix = query.get("prefix", [""])[0]
            marker = query.get("marker", [""])[0]
            try:
                max_keys = int(query.get("max-keys", ["1000"])[0])
            except ValueError:
                self._error(req, 400, "InvalidArgument")
                return
            if max_keys < 0:
                self._error(req, 400, "InvalidArgument")
                return
            # ranged index read: one page + 1 sentinel for IsTruncated
            # (RGWRados::cls_bucket_list marker pagination)
            page = self._index_page(bucket, marker, prefix,
                                    max_keys + 1)
            keys = sorted(page)
            truncated = len(keys) > max_keys
            keys = keys[:max_keys]
            entries = "".join(
                f"<Contents><Key>{escape(k)}</Key>"
                f"<Size>{page[k]['size']}</Size>"
                f"<ETag>&quot;{page[k]['etag']}&quot;</ETag>"
                "</Contents>"
                for k in keys)
            next_marker = (f"<NextMarker>{escape(keys[-1])}"
                           f"</NextMarker>") if truncated and keys \
                else ""
            self._xml(req, 200,
                      "<ListBucketResult>"
                      f"<Name>{escape(bucket)}</Name>"
                      f"<Prefix>{escape(prefix)}</Prefix>"
                      f"<Marker>{escape(marker)}</Marker>"
                      f"<KeyCount>{len(keys)}</KeyCount>"
                      f"<IsTruncated>{str(truncated).lower()}"
                      f"</IsTruncated>{next_marker}{entries}"
                      "</ListBucketResult>")
        else:
            self._error(req, 405, "MethodNotAllowed")

    # -- object ops --------------------------------------------------------

    def _object_op(self, req, method: str, bucket: str,
                   key: str, body: bytes = b"",
                   query: dict | None = None) -> None:
        query = query or {}
        if not self._bucket_exists(bucket):
            self._error(req, 404, "NoSuchBucket")
            return
        upload_id = query.get("uploadId", [None])[0]
        if method == "POST" and "uploads" in query:
            self._initiate_multipart(req, bucket, key)
            return
        if upload_id is not None:
            if method == "PUT":
                self._upload_part(req, bucket, key, upload_id,
                                  query, body)
            elif method == "POST":
                self._complete_multipart(req, bucket, key, upload_id,
                                         body)
            elif method == "DELETE":
                self._abort_multipart(req, bucket, key, upload_id)
            else:
                self._error(req, 405, "MethodNotAllowed")
            return
        so = StripedObject(self.io, obj_soid(bucket, key))
        if method == "PUT":
            old = self._index_entry(bucket, key)
            if old:
                so.remove()        # overwrite fully replaces
            so.write(body)
            etag = hashlib.md5(body).hexdigest()
            self.io.set_omap(index_oid(bucket), {key: denc.dumps(
                {"size": len(body), "etag": etag,
                 "mtime": _http_date()})})
            self._reply(req, 200, headers={"ETag": f'"{etag}"'})
        elif method in ("GET", "HEAD"):
            ent = self._index_entry(bucket, key)
            if ent is None:
                self._error(req, 404, "NoSuchKey")
                return
            data = so.read() if method == "GET" else b""
            req.send_response(200)
            # GET: length of what we actually send (a concurrent
            # overwrite can race the index read); HEAD: index size
            req.send_header("Content-Length",
                            str(len(data)) if method == "GET"
                            else str(ent["size"]))
            req.send_header("ETag", f'"{ent["etag"]}"')
            req.send_header("Last-Modified", ent["mtime"])
            req.send_header("Content-Type",
                            "application/octet-stream")
            req.end_headers()
            if method == "GET":
                req.wfile.write(data)
        elif method == "DELETE":
            if self._index_entry(bucket, key) is not None:
                so.remove()
                self.io.rm_omap_keys(index_oid(bucket), [key])
            self._reply(req, 204)
        else:
            self._error(req, 405, "MethodNotAllowed")

    # -- multipart upload (RGWInitMultipart/RGWCompleteMultipart) ----------

    def _initiate_multipart(self, req, bucket: str, key: str) -> None:
        import uuid
        upload_id = uuid.uuid4().hex[:16]
        self.io.set_omap(uploads_oid(bucket), {upload_id: denc.dumps(
            {"key": key, "started": _http_date()})})
        self._xml(req, 200,
                  "<InitiateMultipartUploadResult>"
                  f"<Bucket>{escape(bucket)}</Bucket>"
                  f"<Key>{escape(key)}</Key>"
                  f"<UploadId>{upload_id}</UploadId>"
                  "</InitiateMultipartUploadResult>")

    def _upload_meta(self, bucket: str, upload_id: str) -> dict | None:
        try:
            got = self.io.get_omap_keys(uploads_oid(bucket),
                                        [upload_id])
        except RadosError:
            return None
        blob = got.get(upload_id)
        return denc.loads(blob) if blob else None

    def _upload_part(self, req, bucket: str, key: str, upload_id: str,
                     query: dict, body: bytes) -> None:
        meta = self._upload_meta(bucket, upload_id)
        if meta is None or meta["key"] != key:
            self._error(req, 404, "NoSuchUpload")
            return
        try:
            n = int(query.get("partNumber", ["0"])[0])
        except ValueError:
            n = 0
        if not 1 <= n <= 10000:
            self._error(req, 400, "InvalidPartNumber")
            return
        StripedObject(self.io,
                      part_soid(bucket, key, upload_id, n)).write(body)
        etag = hashlib.md5(body).hexdigest()
        self.io.set_omap(parts_oid(bucket, upload_id), {
            f"{n:05d}": denc.dumps({"etag": etag,
                                    "size": len(body)})})
        self._reply(req, 200, headers={"ETag": f'"{etag}"'})

    def _complete_multipart(self, req, bucket: str, key: str,
                            upload_id: str, body: bytes) -> None:
        import re
        meta = self._upload_meta(bucket, upload_id)
        if meta is None or meta["key"] != key:
            self._error(req, 404, "NoSuchUpload")
            return
        try:
            parts = {int(k): denc.loads(v) for k, v in
                     self.io.get_omap(parts_oid(bucket,
                                                upload_id)).items()}
        except RadosError:
            parts = {}
        want = [int(m) for m in
                re.findall(r"<PartNumber>(\d+)</PartNumber>",
                           body.decode("utf-8", "replace"))] \
            if body else sorted(parts)
        if not want or any(n not in parts for n in want):
            self._error(req, 400, "InvalidPart")
            return
        if any(b <= a for a, b in zip(want, want[1:])):
            # S3 requires strictly ascending part numbers — which also
            # rejects duplicates (a part listed twice would be
            # concatenated twice into the final object)
            self._error(req, 400, "InvalidPartOrder")
            return
        # assemble: copy each part into the final object at its
        # cumulative offset (RGWCompleteMultipart assembles via the
        # manifest; here data moves once through the striper)
        final = StripedObject(self.io, obj_soid(bucket, key))
        if self._index_entry(bucket, key) is not None:
            final.remove()
        offset = 0
        md5s = []
        for n in want:
            data = StripedObject(
                self.io, part_soid(bucket, key, upload_id, n)).read()
            final.write(data, offset=offset)
            offset += len(data)
            md5s.append(hashlib.md5(data).digest())
        etag = hashlib.md5(b"".join(md5s)).hexdigest() + \
            f"-{len(want)}"
        self.io.set_omap(index_oid(bucket), {key: denc.dumps(
            {"size": offset, "etag": etag, "mtime": _http_date()})})
        self._cleanup_upload(bucket, key, upload_id, parts)
        self._xml(req, 200,
                  "<CompleteMultipartUploadResult>"
                  f"<Bucket>{escape(bucket)}</Bucket>"
                  f"<Key>{escape(key)}</Key>"
                  f"<ETag>&quot;{etag}&quot;</ETag>"
                  "</CompleteMultipartUploadResult>")

    def _abort_multipart(self, req, bucket: str, key: str,
                         upload_id: str) -> None:
        meta = self._upload_meta(bucket, upload_id)
        if meta is None:
            self._error(req, 404, "NoSuchUpload")
            return
        try:
            parts = {int(k): denc.loads(v) for k, v in
                     self.io.get_omap(parts_oid(bucket,
                                                upload_id)).items()}
        except RadosError:
            parts = {}
        self._cleanup_upload(bucket, meta["key"], upload_id, parts)
        self._reply(req, 204)

    def _cleanup_upload(self, bucket: str, key: str, upload_id: str,
                        parts: dict) -> None:
        for n in parts:
            try:
                StripedObject(self.io, part_soid(bucket, key,
                                                 upload_id, n)).remove()
            except RadosError:
                pass
        try:
            self.io.remove_object(parts_oid(bucket, upload_id))
        except RadosError:
            pass
        try:
            self.io.rm_omap_keys(uploads_oid(bucket), [upload_id])
        except RadosError:
            pass

    def _list_uploads(self, req, bucket: str) -> None:
        try:
            ups = {k: denc.loads(v) for k, v in
                   self.io.get_omap(uploads_oid(bucket)).items()}
        except RadosError:
            ups = {}
        entries = "".join(
            f"<Upload><Key>{escape(m['key'])}</Key>"
            f"<UploadId>{uid}</UploadId>"
            f"<Initiated>{m['started']}</Initiated></Upload>"
            for uid, m in sorted(ups.items()))
        self._xml(req, 200,
                  "<ListMultipartUploadsResult>"
                  f"<Bucket>{escape(bucket)}</Bucket>{entries}"
                  "</ListMultipartUploadsResult>")


def _http_date() -> str:
    return time.strftime("%a, %d %b %Y %H:%M:%S GMT", time.gmtime())


def sign_v2(method: str, path: str, date: str, access: str,
            secret: str) -> str:
    """Client-side helper producing the Authorization header."""
    to_sign = "\n".join([method, "", "", date, path])
    sig = base64.b64encode(hmac.new(
        secret.encode(), to_sign.encode(), hashlib.sha1).digest()
    ).decode()
    return f"AWS {access}:{sig}"
