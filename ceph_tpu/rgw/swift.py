"""Swift REST dialect (rgw/rgw_rest_swift.cc reduced): the same
buckets/objects the S3 surface serves, spoken as Swift v1 — matching
radosgw, where S3 buckets and Swift containers are one namespace.

Surface:
    GET  /auth/v1.0                  TempAuth: X-Auth-User/X-Auth-Key
                                     -> X-Auth-Token + X-Storage-Url
    GET  /v1/AUTH_<acct>             list containers (text or ?format=json)
    PUT  /v1/AUTH_<acct>/<cont>      create container (201)
    DELETE /v1/AUTH_<acct>/<cont>    delete container (204/409)
    GET  /v1/AUTH_<acct>/<cont>      list objects (?prefix=&marker=&format=)
    PUT  /v1/AUTH_<acct>/<cont>/<obj>   upload (201 + ETag)
    GET|HEAD /v1/.../<obj>           download / stat
    DELETE /v1/.../<obj>             remove (204)

The token is stateless TempAuth with an embedded mint timestamp:
"<ts>_<HMAC(secret, access:ts)>".  Possession of the account
credentials mints it, every /v1 request must carry it when the gateway
has auth enabled, and dispatch() enforces a validity window (mirroring
the v4 15-minute request-skew grace) — a leaked token expires instead
of being forever as good as the credentials themselves.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import time

from ..client.striper import StripedObject
from . import ver_soid

TOKEN_TTL = 900.0        # seconds a minted token stays valid
TOKEN_SKEW = 60.0        # tolerated clock skew for ts-in-the-future


def mint_token(access: str, secret: str, now: float | None = None) -> str:
    ts = int(time.time() if now is None else now)
    sig = hmac.new(secret.encode(), f"swift:{access}:{ts}".encode(),
                   hashlib.sha256).hexdigest()
    return f"{ts}_{sig}"


def check_token(access: str, secret: str, token: str,
                now: float | None = None) -> bool:
    """Valid signature AND inside the validity window."""
    ts_s, _, sig = token.partition("_")
    if not sig or not ts_s.isdigit():
        return False
    ts = int(ts_s)
    now = time.time() if now is None else now
    if not (ts - TOKEN_SKEW <= now <= ts + TOKEN_TTL):
        return False
    want = hmac.new(secret.encode(), f"swift:{access}:{ts}".encode(),
                    hashlib.sha256).hexdigest()
    return hmac.compare_digest(sig, want)


def handles(path: str) -> bool:
    return path == "/auth/v1.0" or path.startswith("/v1/") \
        or path == "/v1"


def dispatch(gw, req, method: str, path: str, query: dict,
             body: bytes) -> None:
    """Route a Swift-dialect request against the gateway's store."""
    if path == "/auth/v1.0":
        _auth(gw, req)
        return
    if gw.access_key:
        token = req.headers.get("X-Auth-Token", "")
        if not check_token(gw.access_key, gw.secret_key, token):
            gw._reply(req, 401, b"Unauthorized")
            return
    parts = [p for p in path.split("/") if p][1:]   # drop "v1"
    if parts and parts[0].startswith("AUTH_"):
        parts = parts[1:]
    if not parts:
        _account(gw, req, method, query)
    elif len(parts) == 1:
        _container(gw, req, method, parts[0], query)
    else:
        _object(gw, req, method, parts[0], "/".join(parts[1:]), body)


def _auth(gw, req) -> None:
    user = req.headers.get("X-Auth-User", "")
    key = req.headers.get("X-Auth-Key", "")
    if gw.access_key and not (
            user.split(":")[0] == gw.access_key
            and hmac.compare_digest(key, gw.secret_key)):
        gw._reply(req, 401, b"Unauthorized")
        return
    host = req.headers.get("Host", "127.0.0.1")
    gw._reply(req, 200, b"", {
        "X-Auth-Token": mint_token(gw.access_key, gw.secret_key),
        "X-Storage-Url": f"http://{host}/v1/AUTH_"
                         f"{gw.access_key or 'anon'}",
    })


def _account(gw, req, method: str, query: dict) -> None:
    if method not in ("GET", "HEAD"):
        gw._reply(req, 405, b"")
        return
    names = sorted(gw._buckets())
    if query.get("format", [""])[0] == "json":
        out = json.dumps([{"name": n} for n in names]).encode()
        gw._reply(req, 200, out,
                  {"Content-Type": "application/json"})
    else:
        gw._reply(req, 200,
                  ("".join(f"{n}\n" for n in names)).encode(),
                  {"Content-Type": "text/plain"})


def _container(gw, req, method: str, cont: str, query: dict) -> None:
    if method == "PUT":
        if gw._bucket_exists(cont):
            gw._reply(req, 202, b"")      # Swift: re-PUT is accepted
            return
        gw._create_bucket(cont)
        gw._reply(req, 201, b"")
    elif method == "DELETE":
        if not gw._bucket_exists(cont):
            gw._reply(req, 404, b"")
            return
        if not gw._index_empty(cont):
            # includes delete-marker entries: a versioned container
            # must be purged through the S3 version surface first
            # (Swift exposes no version-purge op) — a marker still
            # guards hidden version data
            gw._reply(req, 409, b"")
            return
        gw._remove_bucket(cont)
        gw._reply(req, 204, b"")
    elif method in ("GET", "HEAD"):
        if not gw._bucket_exists(cont):
            gw._reply(req, 404, b"")
            return
        prefix = query.get("prefix", [""])[0]
        marker = query.get("marker", [""])[0]
        page = gw._index_page(cont, marker, prefix, 10000)
        entries = [(k, v) for k, v in sorted(page.items())
                   if not v.get("delete_marker")]
        if query.get("format", [""])[0] == "json":
            out = json.dumps([
                {"name": k, "bytes": v.get("size", 0),
                 "hash": v.get("etag", ""),
                 "last_modified": v.get("mtime", "")}
                for k, v in entries]).encode()
            gw._reply(req, 200, out,
                      {"Content-Type": "application/json"})
        else:
            gw._reply(req, 200,
                      ("".join(f"{k}\n" for k, _v in
                               entries)).encode(),
                      {"Content-Type": "text/plain"})
    else:
        gw._reply(req, 405, b"")


def _object(gw, req, method: str, cont: str, key: str,
            body: bytes) -> None:
    if not gw._bucket_exists(cont):
        gw._reply(req, 404, b"")
        return
    if method == "PUT":
        # same store path as an S3 put on an unversioned bucket
        meta = gw._bucket_meta(cont) or {}
        gw._put_object(req, cont, key, body,
                       meta.get("versioning", ""),
                       swift_status=201)
    elif method in ("GET", "HEAD"):
        ent = gw._index_entry(cont, key)
        if ent is None or ent.get("delete_marker"):
            gw._reply(req, 404, b"")
            return
        vid = ent.get("version_id", "null")
        data = b""
        if method == "GET":
            data = StripedObject(gw.io,
                                 ver_soid(cont, key, vid)).read()
        gw._reply(req, 200, data, {
            "ETag": ent.get("etag", ""),
            "Last-Modified": ent.get("mtime", ""),
            "Content-Type": "application/octet-stream",
            **({"Content-Length": str(ent.get("size", 0))}
               if method == "HEAD" else {}),
        })
    elif method == "DELETE":
        if gw._index_entry(cont, key) is None:
            gw._reply(req, 404, b"")
            return
        meta = gw._bucket_meta(cont) or {}
        # shares the S3 delete path: versioned containers get delete
        # markers, unversioned ones remove outright; bilog either way
        gw._delete_object(req, cont, key, None,
                          meta.get("versioning", ""))
    else:
        gw._reply(req, 405, b"")
