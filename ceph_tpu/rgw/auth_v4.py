"""AWS Signature Version 4 for the S3 dialect.

The reference computes v4 signatures in rgw/rgw_auth_s3.{h,cc}
(rgw_create_s3_v4_canonical_request, rgw_calculate_s3_v4_aws_signature,
rgw/rgw_auth_s3.h:24-32): canonical request -> string-to-sign -> HMAC
chain keyed AWS4+secret over date/region/service.  This module is both
the client-side signer (tests use it to produce signed requests) and
the server-side verifier (RGWDaemon rebuilds the canonical request
from what actually arrived and compares digests).

Scope pins match the reference's S3 defaults: single region
("default"), service "s3", header-carried signatures (presigned URLs
are not in scope).
"""

from __future__ import annotations

import hashlib
import hmac
import time
from urllib.parse import quote

ALGORITHM = "AWS4-HMAC-SHA256"
UNSIGNED = "UNSIGNED-PAYLOAD"
REGION = "default"
SERVICE = "s3"


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def _sha256_hex(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def signing_key(secret: str, date: str, region: str = REGION,
                service: str = SERVICE) -> bytes:
    """The v4 key-derivation chain (rgw_auth_s3.h
    rgw_calculate_s3_v4_aws_signature's inner HMAC ladder)."""
    k = _hmac(b"AWS4" + secret.encode(), date)
    k = _hmac(k, region)
    k = _hmac(k, service)
    return _hmac(k, "aws4_request")


def canonical_query(raw_query: str) -> str:
    """Sorted, strictly-encoded query string.  Operates on the RAW
    query (before any unquoting) so an encoded '&' in a value cannot
    split into extra parameters."""
    if not raw_query:
        return ""
    pairs = []
    for item in raw_query.split("&"):
        if not item:
            continue
        name, _, value = item.partition("=")
        # normalize percent-encoding: decode then re-encode with the
        # v4 unreserved set
        from urllib.parse import unquote_plus
        pairs.append((quote(unquote_plus(name), safe="-_.~"),
                      quote(unquote_plus(value), safe="-_.~")))
    return "&".join(f"{n}={v}" for n, v in sorted(pairs))


def canonical_request(method: str, path: str, raw_query: str,
                      headers: dict, signed_headers: list[str],
                      payload_hash: str) -> str:
    """rgw_create_s3_v4_canonical_request: the 6-line canonical form.
    `headers` maps lowercase name -> value as they appear on the wire;
    `path` is the already-decoded URI path, re-encoded per segment."""
    canon_uri = quote(path, safe="/-_.~") or "/"
    canon_headers = "".join(
        f"{h}:{' '.join(str(headers.get(h, '')).split())}\n"
        for h in signed_headers)
    return "\n".join([
        method, canon_uri, canonical_query(raw_query), canon_headers,
        ";".join(signed_headers), payload_hash])


def string_to_sign(timestamp: str, scope: str, creq: str) -> str:
    return "\n".join([ALGORITHM, timestamp, scope, _sha256_hex(
        creq.encode())])


def sign_v4(method: str, path: str, raw_query: str, headers: dict,
            payload: bytes, access: str, secret: str,
            timestamp: str | None = None,
            region: str = REGION) -> dict:
    """Client-side: return the headers to attach (Authorization,
    x-amz-date, x-amz-content-sha256).  `headers` should already hold
    `host`."""
    timestamp = timestamp or time.strftime("%Y%m%dT%H%M%SZ",
                                           time.gmtime())
    date = timestamp[:8]
    payload_hash = _sha256_hex(payload)
    hdrs = {k.lower(): v for k, v in headers.items()}
    hdrs["x-amz-date"] = timestamp
    hdrs["x-amz-content-sha256"] = payload_hash
    signed = sorted(set(hdrs) | {"x-amz-date", "x-amz-content-sha256"})
    scope = f"{date}/{region}/{SERVICE}/aws4_request"
    creq = canonical_request(method, path, raw_query, hdrs, signed,
                             payload_hash)
    sts = string_to_sign(timestamp, scope, creq)
    sig = hmac.new(signing_key(secret, date, region), sts.encode(),
                   hashlib.sha256).hexdigest()
    return {
        "Authorization": (
            f"{ALGORITHM} Credential={access}/{scope}, "
            f"SignedHeaders={';'.join(signed)}, Signature={sig}"),
        "x-amz-date": timestamp,
        "x-amz-content-sha256": payload_hash,
    }


def parse_auth_header(header: str) -> dict | None:
    """Split `AWS4-HMAC-SHA256 Credential=..., SignedHeaders=...,
    Signature=...` into its parts; None if malformed."""
    if not header.startswith(ALGORITHM + " "):
        return None
    fields = {}
    for part in header[len(ALGORITHM) + 1:].split(","):
        name, _, value = part.strip().partition("=")
        fields[name] = value
    cred = fields.get("Credential", "")
    access, _, scope = cred.partition("/")
    if not access or not scope or "Signature" not in fields:
        return None
    return {
        "access": access,
        "scope": scope,
        "signed_headers": [h for h in
                           fields.get("SignedHeaders", "").split(";")
                           if h],
        "signature": fields["Signature"],
    }


def verify_v4(method: str, path: str, raw_query: str, headers: dict,
              payload: bytes, access: str, secret: str) -> bool:
    """Server-side: rebuild the canonical request from the request as
    received and compare signatures (and the payload digest, unless
    the client declared UNSIGNED-PAYLOAD)."""
    auth = parse_auth_header(headers.get("authorization", ""))
    if auth is None or auth["access"] != access:
        return False
    scope_parts = auth["scope"].split("/")
    if len(scope_parts) != 4 or scope_parts[3] != "aws4_request" \
            or scope_parts[2] != SERVICE:
        return False
    date, region = scope_parts[0], scope_parts[1]
    timestamp = headers.get("x-amz-date", "")
    if not timestamp.startswith(date):
        return False
    try:
        import calendar
        ts = calendar.timegm(time.strptime(timestamp,
                                           "%Y%m%dT%H%M%SZ"))
    except ValueError:
        return False
    if abs(time.time() - ts) > 900:
        return False          # outside the 15-min grace window: a
        # captured request must not verify forever (RGW_AUTH_GRACE)
    declared = headers.get("x-amz-content-sha256", UNSIGNED)
    if declared != UNSIGNED and declared != _sha256_hex(payload):
        return False          # body does not match its signed digest
    signed = auth["signed_headers"]
    if "host" not in signed or "x-amz-date" not in signed:
        return False          # v4 requires these to be signed
    creq = canonical_request(method, path, raw_query, headers, signed,
                             declared)
    sts = string_to_sign(timestamp, auth["scope"], creq)
    want = hmac.new(signing_key(secret, date, region), sts.encode(),
                    hashlib.sha256).hexdigest()
    return hmac.compare_digest(want, auth["signature"])
