"""RGW multisite sync: a secondary zone pulls from a primary over S3.

The rgw_data_sync.h model (rgw_data_sync_info's StateFullSync ->
StateIncrementalSync per bucket shard) reduced to its working core:

  * FULL SYNC: list the peer's buckets, mirror bucket metadata
    (versioning flag included), list each bucket and copy every
    current object;
  * INCREMENTAL: poll each bucket's replication log (the cls_rgw
    bilog analog, served at ``GET /bucket?bilog&marker=N``) and apply
    each entry — put (fetch + store), delete, delete-marker — keeping
    a durable per-bucket marker in the local zone's RADOS, so a
    restarted agent resumes where it left off.

Reductions vs the reference (documented scope): object VERSION
HISTORIES are not mirrored — a versioned bucket's current objects and
delete markers replicate, matching what a reader of the secondary
observes; multi-shard bilogs and inter-zone ACLs are out of scope.
Requests to the peer are SigV4-signed when credentials are given.

FAILURE MODEL (the "front doors under fire" hardening): the agent
must degrade, not wedge or tight-loop.  Every peer request consults
the FaultSet partition rules (zones talk HTTP, not the messenger, so
the net-fault plane is applied here explicitly); a failed bucket is
retried a bounded number of times in-round (``rgw_sync_retries``) and
then QUARANTINED under per-bucket exponential backoff
(``rgw_sync_backoff_base`` doubling to ``rgw_sync_backoff_max``) so
one unreachable/corrupt bucket cannot stall the others; a failed
discovery round backs the whole agent off on the same curve.  All of
it is counted in the ``rgw_sync`` perf block (sync_errors /
sync_retries / sync_backoff_secs ...), and the per-bucket cursors are
durable in the local zone's RADOS — a gateway crash or OSD
kill+rebirth mid-sync resumes from the last saved marker.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from urllib.parse import quote, urlparse
from xml.sax.saxutils import unescape

from ..client.rados import RadosError
from ..utils import denc, faults
from ..utils.perf_counters import PerfCountersBuilder
from . import auth_v4, index_oid

SYNC_STATE_OID = "rgw.sync.state"     # omap: bucket -> marker state


class RGWSyncAgent:
    """Runs inside the SECONDARY zone's gateway process: pulls from
    `peer_url` and applies into the local RGWDaemon's store."""

    def __init__(self, gw, peer_url: str, access_key: str = "",
                 secret_key: str = "", interval: float = 0.5,
                 entity: str | None = None,
                 peer_entity: str | None = None, conf=None):
        self.gw = gw                      # local RGWDaemon
        self.peer = peer_url.rstrip("/")
        self.access_key = access_key
        self.secret_key = secret_key
        self.interval = interval
        # FaultSet addresses: partition rules match these (zone links
        # are HTTP, so the agent applies the net-fault plane itself)
        self.entity = entity or f"rgw.{gw.port}"
        self.peer_entity = peer_entity or \
            f"rgw.{urlparse(self.peer).port}"
        self.conf = conf if conf is not None \
            else getattr(gw.rados, "conf", None)
        self.log_prefix = f"rgw-sync<{self.peer}>"
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.errors = 0
        self.perf = (PerfCountersBuilder("rgw_sync")
                     .add_u64_counter("sync_rounds")
                     .add_u64_counter("sync_errors")
                     .add_u64_counter("sync_retries")
                     .add_u64_counter("sync_quarantines")
                     .add_u64_counter("sync_objects_copied")
                     .add_u64_counter("sync_deletes_applied")
                     .add_time("sync_backoff_secs")
                     .create_perf_counters())
        # bucket -> {"failures": n, "until": monotonic}: a quarantined
        # bucket sits out rounds until its backoff deadline passes
        self._quarantine: dict[str, dict] = {}
        self._round_failures = 0
        self._round_until = 0.0

    # -- knobs -------------------------------------------------------------

    def _knob(self, name: str, default):
        return getattr(self.conf, name, default) \
            if self.conf is not None else default

    def _backoff(self, failures: int) -> float:
        base = float(self._knob("rgw_sync_backoff_base", 0.5))
        cap = float(self._knob("rgw_sync_backoff_max", 10.0))
        return min(base * (2 ** max(0, failures - 1)), cap)

    def perf_dump(self) -> dict:
        """The ``perf dump rgw_sync`` block (schema pinned by
        tests/test_observability.py)."""
        out = self.perf.dump()
        out["quarantined_buckets"] = sorted(self._quarantine)
        return {"rgw_sync": out}

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "RGWSyncAgent":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="rgw-sync")
        self._thread.start()
        return self

    def shutdown(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)

    # -- peer REST ---------------------------------------------------------

    def _req(self, method: str, path: str, raw_query: str = "",
             data: bytes = b"") -> bytes:
        if faults.get().partitioned(self.entity, self.peer_entity):
            # the zone link is HTTP: a messenger-style partition rule
            # must still sever it — surface as the transport error an
            # unreachable peer would produce
            raise OSError(f"partitioned: {self.entity} -x-> "
                          f"{self.peer_entity}")
        host = urlparse(self.peer).netloc
        headers: dict = {"Host": host}
        if self.access_key:
            headers.update(auth_v4.sign_v4(
                method, path, raw_query, {"host": host}, data,
                self.access_key, self.secret_key))
        url = self.peer + quote(path) + \
            (f"?{raw_query}" if raw_query else "")
        r = urllib.request.Request(url, data=data or None,
                                   method=method, headers=headers)
        with urllib.request.urlopen(r, timeout=30) as resp:
            return resp.read()

    # -- durable per-bucket markers ---------------------------------------

    def _state(self) -> dict[str, dict]:
        try:
            raw = self.gw.io.get_omap(SYNC_STATE_OID)
        except RadosError:
            return {}
        return {b: denc.loads(v) for b, v in raw.items()}

    def _save_state(self, bucket: str, st: dict) -> None:
        self.gw.io.set_omap(SYNC_STATE_OID, {bucket: denc.dumps(st)})

    # -- sync passes -------------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            if time.monotonic() < self._round_until:
                continue          # round-level backoff: poll, don't spin
            try:
                self.sync_once()
                self._round_failures = 0
            except Exception:
                # a failed DISCOVERY (peer unreachable/partitioned):
                # back the whole agent off exponentially instead of
                # tight-looping against a dead link
                self.errors += 1
                self.perf.inc("sync_errors")
                self._round_failures += 1
                backoff = self._backoff(self._round_failures)
                self._round_until = time.monotonic() + backoff
                self.perf.tinc("sync_backoff_secs", backoff)

    def sync_once(self) -> None:
        """One round: discover buckets, full-sync the new ones,
        incremental the rest.  A bucket that fails its bounded
        in-round retries is quarantined (skipped under exponential
        backoff) so the other buckets keep replicating."""
        import re
        self.perf.inc("sync_rounds")
        body = self._req("GET", "/").decode()
        buckets = [unescape(b) for b in
                   re.findall(r"<Name>([^<]+)</Name>", body)]
        retries = max(0, int(self._knob("rgw_sync_retries", 3)))
        now = time.monotonic()
        for bucket in buckets:
            q = self._quarantine.get(bucket)
            if q is not None and now < q["until"]:
                continue                   # still backing off
            if q is not None:
                self.perf.inc("sync_retries")   # post-backoff retry
            self._sync_bucket_bounded(bucket, retries, q)

    def _sync_bucket_bounded(self, bucket: str, retries: int,
                             q: dict | None) -> None:
        prior_failures = q["failures"] if q else 0
        for attempt in range(retries + 1):
            if self._stop.is_set():
                return
            try:
                # re-read the durable cursor each attempt: a partial
                # full sync saved progress before it failed
                st = self._state().get(bucket)
                if st is None or st.get("stage") == "full":
                    self._full_sync(bucket, st or {})
                else:
                    self._incremental(bucket, st)
                self._quarantine.pop(bucket, None)
                return
            except Exception:
                self.errors += 1
                self.perf.inc("sync_errors")
                if attempt < retries:
                    self.perf.inc("sync_retries")
        failures = prior_failures + 1
        backoff = self._backoff(failures)
        self._quarantine[bucket] = {
            "failures": failures,
            "until": time.monotonic() + backoff}
        self.perf.inc("sync_quarantines")
        self.perf.tinc("sync_backoff_secs", backoff)

    def _mirror_bucket_meta(self, bucket: str) -> None:
        if not self.gw._bucket_exists(bucket):
            self.gw._set_bucket_meta(bucket, {"created": ""})
            try:
                self.gw.io.write_full(index_oid(bucket), b"")
            except RadosError:
                pass
        try:
            vraw = self._req("GET", f"/{bucket}",
                             raw_query="versioning").decode()
        except urllib.error.HTTPError:
            return
        meta = self.gw._bucket_meta(bucket) or {"created": ""}
        for status in ("Enabled", "Suspended"):
            if f"<Status>{status}</Status>" in vraw:
                if meta.get("versioning") != status:
                    meta["versioning"] = status
                    self.gw._set_bucket_meta(bucket, meta)
                break

    def _full_sync(self, bucket: str, st: dict) -> None:
        """StateFullSync: pin the log position FIRST, then copy the
        listing — ops racing the copy land in the log and replay in
        the incremental stage (at-least-once, puts are idempotent)."""
        import re
        self._mirror_bucket_meta(bucket)
        if "marker" in st:
            # resuming a crashed full sync: keep the ORIGINAL pin —
            # ops logged while we were down must replay incrementally
            pinned = int(st["marker"])
        else:
            entries = json.loads(self._req(
                "GET", f"/{bucket}",
                raw_query="bilog&marker=0") or b"[]")
            pinned = max((e["seq"] for e in entries), default=0)
        marker = st.get("listing_marker", "")
        while True:
            q = "max-keys=100" + (f"&marker={quote(marker)}"
                                  if marker else "")
            body = self._req("GET", f"/{bucket}",
                             raw_query=q).decode()
            keys = [unescape(k) for k in
                    re.findall(r"<Key>([^<]+)</Key>", body)]
            for key in keys:
                self._copy_object(bucket, key)
            if "<IsTruncated>true</IsTruncated>" not in body \
                    or not keys:
                break
            marker = keys[-1]
            self._save_state(bucket, {"stage": "full",
                                      "listing_marker": marker,
                                      "marker": pinned})
        self._save_state(bucket, {"stage": "incr", "marker": pinned})

    def _incremental(self, bucket: str, st: dict) -> None:
        marker = int(st.get("marker", 0))
        entries = json.loads(self._req(
            "GET", f"/{bucket}",
            raw_query=f"bilog&marker={marker}") or b"[]")
        for ent in entries:
            op, key = ent.get("op"), ent.get("key", "")
            if op == "put":
                self._copy_object(bucket, key)
            elif op in ("delete", "delete-marker"):
                try:
                    self._apply_local("DELETE", bucket, key)
                    self.perf.inc("sync_deletes_applied")
                except urllib.error.HTTPError:
                    pass
            elif op == "delete-version":
                # version histories aren't mirrored: re-copy the
                # current object (covers marker-removal restores),
                # deleting when nothing current remains
                self._copy_object(bucket, key)
            marker = ent["seq"]
            self._save_state(bucket, {"stage": "incr",
                                      "marker": marker})

    def _copy_object(self, bucket: str, key: str) -> None:
        try:
            data = self._req("GET", f"/{bucket}/{key}")
        except urllib.error.HTTPError as e:
            if e.code == 404:
                try:
                    self._apply_local("DELETE", bucket, key)
                    self.perf.inc("sync_deletes_applied")
                except urllib.error.HTTPError:
                    pass
                return
            raise
        self._apply_local("PUT", bucket, key, data)
        self.perf.inc("sync_objects_copied")

    def _apply_local(self, method: str, bucket: str, key: str,
                     data: bytes = b"") -> None:
        """Apply through the LOCAL gateway's HTTP surface so index,
        versioning and bilog bookkeeping all engage."""
        host = f"127.0.0.1:{self.gw.port}"
        headers: dict = {"Host": host}
        if self.gw.access_key:
            headers.update(auth_v4.sign_v4(
                method, f"/{bucket}/{key}", "", {"host": host}, data,
                self.gw.access_key, self.gw.secret_key))
        r = urllib.request.Request(
            f"http://{host}/{quote(bucket)}/{quote(key)}",
            data=data if method == "PUT" else None,
            method=method, headers=headers)
        with urllib.request.urlopen(r, timeout=30):
            pass
