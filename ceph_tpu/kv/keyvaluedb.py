"""Uniform transactional KV API.

Mirrors kv/KeyValueDB.h semantics: keys live in (prefix, key) namespaces,
writes are batched in transactions submitted atomically, iteration is
ordered within a prefix.
"""

from __future__ import annotations

import abc
from typing import Iterable, Iterator


class KVTransaction:
    """A write batch: (op, prefix, key, value) entries."""

    __slots__ = ("ops",)

    def __init__(self):
        self.ops: list[tuple] = []

    def set(self, prefix: str, key: str, value: bytes) -> None:
        self.ops.append(("set", prefix, key, bytes(value)))

    def rmkey(self, prefix: str, key: str) -> None:
        self.ops.append(("rm", prefix, key, b""))

    def rmkeys_by_prefix(self, prefix: str) -> None:
        self.ops.append(("rm_prefix", prefix, "", b""))

    def merge(self, other: "KVTransaction") -> None:
        self.ops.extend(other.ops)


class KeyValueDB(abc.ABC):
    @abc.abstractmethod
    def open(self) -> None: ...

    @abc.abstractmethod
    def close(self) -> None: ...

    def transaction(self) -> KVTransaction:
        return KVTransaction()

    @abc.abstractmethod
    def submit_transaction(self, txn: KVTransaction,
                           sync: bool = False) -> None:
        """Apply atomically; sync=True -> durable before return."""

    @abc.abstractmethod
    def get(self, prefix: str, key: str) -> bytes | None: ...

    def get_multi(self, prefix: str, keys: Iterable[str]) -> dict[str, bytes]:
        out = {}
        for k in keys:
            v = self.get(prefix, k)
            if v is not None:
                out[k] = v
        return out

    @abc.abstractmethod
    def iterate(self, prefix: str, start: str = "",
                end: str | None = None) -> Iterator[tuple[str, bytes]]:
        """Ordered (key, value) pairs with start <= key < end."""

    @abc.abstractmethod
    def prefixes(self) -> list[str]:
        """All namespaces with at least one key (store-sync dumps)."""
