"""Transactional key/value abstraction (kv/KeyValueDB.h analog).

Backends: MemDB (sorted in-memory, tests + MemStore omap) and SqliteDB
(durable, the RocksDB stand-in for mon stores and file-store omap —
sqlite3 is in the stdlib; the interface is the contract, the engine is
swappable).
"""

from .keyvaluedb import KeyValueDB, KVTransaction
from .memdb import MemDB
from .sqlitedb import SqliteDB

__all__ = ["KeyValueDB", "KVTransaction", "MemDB", "SqliteDB"]
