"""Durable KV over sqlite3 (the RocksDBStore stand-in).

Same KeyValueDB contract; WAL-mode sqlite gives atomic batched writes
and ordered iteration.  Used by MonitorDBStore and file-store omap.
"""

from __future__ import annotations

import sqlite3
import threading
from typing import Iterator

from .keyvaluedb import KeyValueDB, KVTransaction


class SqliteDB(KeyValueDB):
    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._conn: sqlite3.Connection | None = None

    def open(self) -> None:
        if getattr(self, "_conn", None) is not None:
            self._conn.close()     # mkfs-then-mount must not leak one
        self._conn = sqlite3.connect(self.path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS kv ("
            " prefix TEXT NOT NULL, key TEXT NOT NULL, value BLOB,"
            " PRIMARY KEY (prefix, key))")
        self._conn.commit()

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None

    def submit_transaction(self, txn: KVTransaction,
                           sync: bool = False) -> None:
        with self._lock:
            cur = self._conn.cursor()
            if sync:
                cur.execute("PRAGMA synchronous=FULL")
            try:
                for op, prefix, key, value in txn.ops:
                    if op == "set":
                        cur.execute(
                            "INSERT OR REPLACE INTO kv VALUES (?,?,?)",
                            (prefix, key, value))
                    elif op == "rm":
                        cur.execute(
                            "DELETE FROM kv WHERE prefix=? AND key=?",
                            (prefix, key))
                    elif op == "rm_prefix":
                        cur.execute("DELETE FROM kv WHERE prefix=?",
                                    (prefix,))
                self._conn.commit()
            finally:
                if sync:
                    cur.execute("PRAGMA synchronous=NORMAL")

    def get(self, prefix: str, key: str) -> bytes | None:
        with self._lock:
            row = self._conn.execute(
                "SELECT value FROM kv WHERE prefix=? AND key=?",
                (prefix, key)).fetchone()
        return bytes(row[0]) if row else None

    def prefixes(self) -> list[str]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT DISTINCT prefix FROM kv").fetchall()
        return [r[0] for r in rows]

    def iterate(self, prefix: str, start: str = "",
                end: str | None = None) -> Iterator[tuple[str, bytes]]:
        with self._lock:
            if end is None:
                rows = self._conn.execute(
                    "SELECT key, value FROM kv WHERE prefix=? AND key>=?"
                    " ORDER BY key", (prefix, start)).fetchall()
            else:
                rows = self._conn.execute(
                    "SELECT key, value FROM kv WHERE prefix=? AND key>=?"
                    " AND key<? ORDER BY key", (prefix, start, end)).fetchall()
        for k, v in rows:
            yield k, bytes(v)
