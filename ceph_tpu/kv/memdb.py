"""In-memory sorted KV (kv/MemDB.cc analog); the test/MemStore backend."""

from __future__ import annotations

import threading
from typing import Iterator

from .keyvaluedb import KeyValueDB, KVTransaction


class MemDB(KeyValueDB):
    def __init__(self):
        self._data: dict[str, dict[str, bytes]] = {}
        self._lock = threading.Lock()

    def open(self) -> None:
        pass

    def close(self) -> None:
        pass

    def submit_transaction(self, txn: KVTransaction,
                           sync: bool = False) -> None:
        with self._lock:
            for op, prefix, key, value in txn.ops:
                space = self._data.setdefault(prefix, {})
                if op == "set":
                    space[key] = value
                elif op == "rm":
                    space.pop(key, None)
                elif op == "rm_prefix":
                    space.clear()

    def get(self, prefix: str, key: str) -> bytes | None:
        with self._lock:
            return self._data.get(prefix, {}).get(key)

    def prefixes(self) -> list[str]:
        with self._lock:
            return [p for p, space in self._data.items() if space]

    def iterate(self, prefix: str, start: str = "",
                end: str | None = None) -> Iterator[tuple[str, bytes]]:
        with self._lock:
            items = sorted(self._data.get(prefix, {}).items())
        for k, v in items:
            if k < start:
                continue
            if end is not None and k >= end:
                break
            yield k, v
