"""Message model + wire format.

Counterpart of msg/Message.h + the 131 concrete types in messages/ (the
concrete types live next to their subsystems here: mon/messages.py,
osd/messages.py, ...).  Wire format: fixed header (magic, type id,
payload length, seq) + denc-encoded payload fields — an explicit,
versioned, data-only encoding (utils/denc.py), so decoding a hostile or
corrupt frame raises cleanly and can never execute code.
"""

from __future__ import annotations

import struct
from typing import ClassVar

from ..utils import denc

_HDR = struct.Struct("<4sIQQ")        # magic, type, payload_len, seq
MAGIC = b"CTM1"


class MessageRegistry:
    _types: dict[int, type] = {}

    @classmethod
    def register(cls, type_id: int, klass: type) -> None:
        existing = cls._types.get(type_id)
        if existing is not None and existing is not klass:
            raise ValueError(
                f"message type {type_id} already bound to {existing}")
        cls._types[type_id] = klass

    @classmethod
    def get(cls, type_id: int) -> type | None:
        return cls._types.get(type_id)


def register_message(klass: type) -> type:
    """Class decorator: requires a TYPE class attr."""
    MessageRegistry.register(klass.TYPE, klass)
    return klass


class Message:
    """Base message: subclasses set TYPE and carry picklable attrs."""

    TYPE: ClassVar[int] = 0

    def __init__(self, **fields):
        self.__dict__.update(fields)
        self.src: str = ""          # entity name, e.g. "osd.3"
        self.seq: int = 0

    # -- wire --------------------------------------------------------------

    def encode(self, seq: int = 0) -> bytes:
        payload = denc.dumps(
            {k: v for k, v in self.__dict__.items() if k != "seq"})
        return _HDR.pack(MAGIC, self.TYPE, len(payload), seq) + payload

    @staticmethod
    def header_size() -> int:
        return _HDR.size

    @staticmethod
    def parse_header(buf: bytes) -> tuple[int, int, int]:
        magic, type_id, plen, seq = _HDR.unpack(buf)
        if magic != MAGIC:
            raise ValueError("bad message magic")
        return type_id, plen, seq

    @staticmethod
    def decode(type_id: int, seq: int, payload: bytes) -> "Message":
        klass = MessageRegistry.get(type_id)
        if klass is None:
            raise ValueError(f"unknown message type {type_id}")
        fields = denc.loads(payload)
        if not isinstance(fields, dict):
            raise denc.DencError("message payload must be a field dict")
        msg = klass.__new__(klass)
        msg.__dict__.update(fields)
        msg.seq = seq
        return msg

    def __repr__(self):
        fields = {k: v for k, v in self.__dict__.items()
                  if k not in ("src", "seq") and not k.startswith("_")}
        inner = ", ".join(f"{k}={v!r}" for k, v in list(fields.items())[:6])
        return f"{type(self).__name__}({inner})"
