"""Message model + wire format.

Counterpart of msg/Message.h + the 131 concrete types in messages/ (the
concrete types live next to their subsystems here: mon/messages.py,
osd/messages.py, ...).  Wire format: fixed header (magic, type id,
payload length, seq) + denc-encoded payload fields — an explicit,
versioned, data-only encoding (utils/denc.py), so decoding a hostile or
corrupt frame raises cleanly and can never execute code.

Data segments (CTM2): large byte fields do NOT ride inside the denc
payload.  At encode time the field tree is walked and every bytes-like
leaf >= SEG_THRESHOLD (bytes, bytearray, memoryview, BufferList) is
replaced by a tiny ``_SegRef`` placeholder; the raw bytes ride
out-of-band AFTER the denc payload as an iovec of segments, described
by a segment table between the fixed header and the payload:

    CTM2 header (magic=CTM2, type, body_len, seq)
    u32 nsegs, nsegs * u64 seg length      }  body_len covers the
    denc payload (with _SegRef leaves)     }  table + the payload
    seg 0 bytes ... seg n-1 bytes              (segments follow)

The sender never copies a segment — ``encode_iov`` returns the header,
table, payload and the segment views for a gather write — and the
receiver scatter-reads each segment straight off the socket, so a
payload crosses the messenger without ever being denc-copied into the
field dict and re-joined per send.  Frames with no large fields keep
the CTM1 layout byte-identical (the wire corpus pins it), and decode is
magic-gated: a CTM1 peer's frames always parse.
"""

from __future__ import annotations

import struct
from typing import ClassVar

from ..utils import copyaudit, denc
from ..utils.bufferlist import BufferList

_HDR = struct.Struct("<4sIQQ")        # magic, type, payload_len, seq
MAGIC = b"CTM1"
MAGIC2 = b"CTM2"
_SEG_COUNT = struct.Struct("<I")
_SEG_LEN = struct.Struct("<Q")

# bytes-like fields at or above this size ride out-of-band; below it
# the denc copy is cheaper than a segment-table entry.  Must stay above
# every wire-corpus sample payload so CTM1 framing stays pinned.
SEG_THRESHOLD = 4096
# inline fields at or above this size count as msg.inline host copies
# (below it they are control-field noise, not payload)
_INLINE_AUDIT_FLOOR = 512

_SEG_MAX = 4096            # segments per frame (sanity bound on decode)


@denc.denc_type
class _SegRef:
    """Placeholder a segmented bytes field leaves in the denc tree.
    Needs a real __dict__ (no __slots__): denc encodes instances by
    walking __dict__."""

    def __init__(self, i: int):
        self.i = i

    def __repr__(self):
        return f"_SegRef({self.i})"


def _extract_segments(obj, segs: list):
    """Walk a field tree; large bytes-like leaves move to `segs` and
    are replaced by _SegRef placeholders.  Returns the (possibly
    rebuilt) tree — untouched sub-trees are shared, not copied."""
    if isinstance(obj, BufferList):
        if len(obj) >= SEG_THRESHOLD and len(segs) < _SEG_MAX:
            segs.append(obj)
            return _SegRef(len(segs) - 1)
        return obj.to_bytes()       # small rope: inline (audited)
    if isinstance(obj, (bytes, bytearray, memoryview)):
        if len(obj) >= SEG_THRESHOLD and len(segs) < _SEG_MAX:
            segs.append(obj)
            return _SegRef(len(segs) - 1)
        if len(obj) >= _INLINE_AUDIT_FLOOR:
            # payload-ish field below the segment threshold: it will
            # be denc-copied into the frame — visible to the audit
            # plane (tiny control fields stay unaudited noise)
            copyaudit.note("msg.inline", len(obj))
        return obj
    if isinstance(obj, list):
        out = None
        for i, v in enumerate(obj):
            nv = _extract_segments(v, segs)
            if nv is not v:
                if out is None:
                    out = list(obj)
                out[i] = nv
        return out if out is not None else obj
    if isinstance(obj, tuple):
        items = [_extract_segments(v, segs) for v in obj]
        if any(n is not o for n, o in zip(items, obj)):
            return tuple(items)
        return obj
    if isinstance(obj, dict):
        out = None
        for k, v in obj.items():
            nv = _extract_segments(v, segs)
            if nv is not v:
                if out is None:
                    out = dict(obj)
                out[k] = nv
        return out if out is not None else obj
    return obj


def _substitute_segments(obj, segs: list):
    """Decode-side inverse: _SegRef leaves become the scatter-read
    segment bytes.  Untouched sub-trees are shared, not copied, so
    segment-free messages pass through at walk cost only.

    A _SegRef is attacker-encodable (it is a registered denc type), so
    its index is VALIDATED: out-of-range (or any ref in a frame that
    carried no segments) raises ValueError — the corrupt-frame error
    the messenger's decode handler skips cleanly — and negative
    indices can never silently alias another segment."""
    if isinstance(obj, _SegRef):
        # getattr: denc decodes the raw instance __dict__, so a
        # hostile frame can omit the attribute entirely
        i = getattr(obj, "i", None)
        if not isinstance(i, int) or not 0 <= i < len(segs):
            raise ValueError(
                f"segment ref {i!r} outside {len(segs)} segments")
        return segs[i]
    if isinstance(obj, list):
        out = None
        for i, v in enumerate(obj):
            nv = _substitute_segments(v, segs)
            if nv is not v:
                if out is None:
                    out = list(obj)
                out[i] = nv
        return out if out is not None else obj
    if isinstance(obj, tuple):
        items = [_substitute_segments(v, segs) for v in obj]
        if any(n is not o for n, o in zip(items, obj)):
            return tuple(items)
        return obj
    if isinstance(obj, dict):
        out = None
        for k, v in obj.items():
            nv = _substitute_segments(v, segs)
            if nv is not v:
                if out is None:
                    out = dict(obj)
                out[k] = nv
        return out if out is not None else obj
    return obj


class MessageRegistry:
    _types: dict[int, type] = {}

    @classmethod
    def register(cls, type_id: int, klass: type) -> None:
        existing = cls._types.get(type_id)
        if existing is not None and existing is not klass:
            raise ValueError(
                f"message type {type_id} already bound to {existing}")
        cls._types[type_id] = klass

    @classmethod
    def get(cls, type_id: int) -> type | None:
        return cls._types.get(type_id)


def register_message(klass: type) -> type:
    """Class decorator: requires a TYPE class attr."""
    MessageRegistry.register(klass.TYPE, klass)
    return klass


class Message:
    """Base message: subclasses set TYPE and carry picklable attrs."""

    TYPE: ClassVar[int] = 0

    def __init__(self, **fields):
        self.__dict__.update(fields)
        self.src: str = ""          # entity name, e.g. "osd.3"
        self.seq: int = 0

    # -- wire --------------------------------------------------------------

    def encode_iov(self, seq: int = 0) -> list:
        """Gather-write buffers for this message: [hdr, payload] for a
        segment-free frame (CTM1, byte-identical to the old format) or
        [hdr, segtable, payload, seg...] (CTM2).  Segment buffers are
        the caller's own views — never copied here.

        Underscore-prefixed attrs are LOCAL annotations (a daemon's
        live ``_trk`` TrackedOp, cache-tier ``_cache_internal`` /
        ``_internal_done`` continuations) and never ride the wire —
        they are unencodable live objects, and a trace handle leaking
        into a frame would be a cross-daemon aliasing bug, not data."""
        seg_holders: list = []
        fields = _extract_segments(
            {k: v for k, v in self.__dict__.items()
             if k != "seq" and not k.startswith("_")},
            seg_holders)
        payload = denc.dumps(fields)
        if not seg_holders:
            return [_HDR.pack(MAGIC, self.TYPE, len(payload), seq),
                    payload]
        from ..utils.bufferlist import iov_of
        seg_bufs: list = []
        lens: list[int] = []
        for holder in seg_holders:
            lens.append(len(holder))
            seg_bufs.extend(iov_of(holder))
        table = _SEG_COUNT.pack(len(seg_holders)) + b"".join(
            _SEG_LEN.pack(n) for n in lens)
        hdr = _HDR.pack(MAGIC2, self.TYPE,
                        len(table) + len(payload), seq)
        return [hdr, table, payload, *seg_bufs]

    def encode(self, seq: int = 0) -> bytes:
        """One joined frame (tests/corpus; the messenger gather-writes
        encode_iov instead)."""
        return b"".join(bytes(b) for b in self.encode_iov(seq))

    @staticmethod
    def header_size() -> int:
        return _HDR.size

    @staticmethod
    def parse_header(buf: bytes) -> tuple[int, int, int]:
        """CTM1 header parse (acks, legacy frames)."""
        magic, type_id, plen, seq = _HDR.unpack(buf)
        if magic != MAGIC:
            raise ValueError("bad message magic")
        return type_id, plen, seq

    @staticmethod
    def parse_header_any(buf: bytes) -> tuple[int, int, int, bool]:
        """Magic-gated header parse: (type, body_len, seq, has_segs).
        CTM1 frames parse exactly as before; CTM2 marks the body as
        carrying a segment table."""
        magic, type_id, plen, seq = _HDR.unpack(buf)
        if magic == MAGIC:
            return type_id, plen, seq, False
        if magic == MAGIC2:
            return type_id, plen, seq, True
        raise ValueError("bad message magic")

    @staticmethod
    def parse_seg_table(body: bytes) -> tuple[list[int], bytes]:
        """Split a CTM2 body into (segment lengths, denc payload)."""
        if len(body) < _SEG_COUNT.size:
            raise ValueError("truncated segment table")
        (nsegs,) = _SEG_COUNT.unpack_from(body)
        if nsegs > _SEG_MAX:
            raise ValueError(f"absurd segment count {nsegs}")
        off = _SEG_COUNT.size
        end = off + nsegs * _SEG_LEN.size
        if len(body) < end:
            raise ValueError("truncated segment table")
        lens = [_SEG_LEN.unpack_from(body, off + i * _SEG_LEN.size)[0]
                for i in range(nsegs)]
        return lens, body[end:]

    @staticmethod
    def decode(type_id: int, seq: int, payload: bytes,
               segments: list | None = None) -> "Message":
        klass = MessageRegistry.get(type_id)
        if klass is None:
            raise ValueError(f"unknown message type {type_id}")
        fields = denc.loads(payload)
        if not isinstance(fields, dict):
            raise denc.DencError("message payload must be a field dict")
        # ALWAYS walk: a frame that encodes _SegRef placeholders but
        # carries no (or too few) segments must be rejected here, not
        # leak placeholder objects into message fields
        fields = _substitute_segments(fields, segments or [])
        msg = klass.__new__(klass)
        msg.__dict__.update(fields)
        msg.seq = seq
        return msg

    @staticmethod
    def decode_frame(frame: bytes) -> "Message":
        """Parse one joined frame of either wire version (tools/tests;
        the messenger scatter-reads instead of joining)."""
        hdr = frame[:_HDR.size]
        type_id, plen, seq, has_segs = Message.parse_header_any(hdr)
        body = frame[_HDR.size:_HDR.size + plen]
        if not has_segs:
            return Message.decode(type_id, seq, body)
        lens, payload = Message.parse_seg_table(body)
        segs = []
        off = _HDR.size + plen
        for n in lens:
            segs.append(frame[off:off + n])
            off += n
        return Message.decode(type_id, seq, payload, segs)

    def __repr__(self):
        fields = {k: v for k, v in self.__dict__.items()
                  if k not in ("src", "seq") and not k.startswith("_")}
        inner = ", ".join(f"{k}={v!r}" for k, v in list(fields.items())[:6])
        return f"{type(self).__name__}({inner})"
