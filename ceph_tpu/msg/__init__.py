"""Typed, policy-driven message transport (msg/ analog).

The cluster's communication backend (the reference's Messenger tier,
msg/Messenger.h:40): reliable ordered delivery of typed messages between
named entities over TCP, with per-peer-class Policy (lossy clients vs
lossless cluster peers), dispatcher fan-in, loopback fast-dispatch and
config-driven fault injection (ms_inject_socket_failures).

On a TPU pod the DCN carries this tier; ICI stays inside the device
compute tier (SURVEY.md §5.8) — hence plain asyncio TCP here, no
DPDK/RDMA analog.
"""

from .message import Message, MessageRegistry, register_message
from .messenger import (Connection, Dispatcher, EntityAddr, Messenger,
                        Policy, create_messenger)

__all__ = ["Message", "MessageRegistry", "register_message", "Messenger",
           "Connection", "Dispatcher", "Policy", "EntityAddr",
           "create_messenger"]
