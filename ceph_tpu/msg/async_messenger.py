"""AsyncMessenger: the epoll event-loop serving stack (msg/async).

Selected with ms_type=async.  Public surface, wire format, auth,
lossless resend and reconnect semantics are identical to the blocking
Messenger (the wire-corpus and cross-stack tests pin this); what
changes is the execution model:

  * NO thread per messenger: all messengers in the process multiplex
    their connections onto the shared pool of `ms_async_op_threads`
    EventWorkers (ceph_tpu/msg/async_event.py), so daemon/client
    thread count is flat in both connections and sessions;
  * accepts, handshakes, frame reads and gather writes all run on the
    loops via per-connection state machines (async_conn.py);
  * op submission is decoupled from socket I/O: ms_dispatch runs on
    the worker (the OSD hands off to its op shards immediately, so the
    tracked op's `queue` span still anchors at messenger receive) and
    replies from op-shard threads re-enter the owning loop through its
    wakeup pipe (AsyncConnection.send_message).

An accepted socket starts on the least-loaded worker; once the banner
names the peer it migrates to that connection's home loop so all of a
connection's state stays single-threaded.
"""

from __future__ import annotations

import socket
import threading

from ..utils import faults
from .async_conn import AsyncConnection, _BadBanner, _Sock, \
    _accept_hs_gen, _drive
from .message import Message
from .messenger import EntityAddr, Messenger, Policy

_EVENT_READ = 1


class AsyncMessenger(Messenger):
    def __init__(self, name: str, conf=None):
        super().__init__(name, conf)
        from .async_event import get_pool
        self.pool = get_pool(
            int(getattr(self.conf, "ms_async_op_threads", 3) or 3))
        self.home = self.pool.pick()
        self._conn_lock = threading.Lock()
        self._listener: socket.socket | None = None
        self._accepting: set[_Sock] = set()
        self._stopped = False
        self._running = False

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        if self._running:
            return
        self._running = True
        self.perf.set("event_workers", len(self.pool))
        if self.addr is not None:
            host, port = self.addr
            ls = socket.create_server((host, port), backlog=512)
            ls.setblocking(False)
            self.addr = (host, ls.getsockname()[1])
            self._listener = ls
            self.home.call(self.home._sel_set, ls, _EVENT_READ,
                           self._on_accept_ready)

    def shutdown(self) -> None:
        if not self._running or self._stopped:
            return
        self._stopped = True
        # each worker closes its own share (selectors are not thread-
        # safe), then we wait so every fd is really gone on return —
        # the churn drill pins zero-fd-growth on this
        workers = list(self.pool.workers)
        done = threading.Event()
        remaining = [len(workers)]
        rlock = threading.Lock()

        def _per_worker(w):
            if w is self.home and self._listener is not None:
                try:
                    w._sel_set(self._listener, 0, None)
                except Exception:
                    pass
                try:
                    self._listener.close()
                except OSError:
                    pass
                self._listener = None
            for conn in list(self.conns.values()):
                if conn.worker is w:
                    conn._close()
            with self._conn_lock:
                pend = [s for s in self._accepting if s.worker is w]
            for s in pend:
                s.close()
            with rlock:
                remaining[0] -= 1
                if remaining[0] == 0:
                    done.set()
        for w in workers:
            w.call(_per_worker, w)
        if threading.current_thread() not in workers:
            done.wait(5)

    # -- loop helpers --------------------------------------------------

    def _loop_call(self, fn, *args) -> None:
        self.home.call(fn, *args)

    def call_later(self, delay: float, fn, *args):
        """Cancelable timer on the home loop (replaces per-session
        helper threads like the monc subscription renewer)."""
        return self.home.call_later(delay, fn, *args)

    def event_stats(self) -> dict:
        return {"type": "async", "workers": len(self.pool),
                "connections": len(self.conns),
                "per_worker": self.pool.stats()}

    # -- outgoing ------------------------------------------------------

    def get_connection(self, peer_name: str,
                       peer_addr: EntityAddr) -> AsyncConnection:
        with self._conn_lock:
            conn = self.conns.get(peer_name)
            if conn is not None and not conn._closed:
                if conn.peer_addr == peer_addr:
                    return conn
                # peer rebooted at a new address (see Messenger)
                conn.mark_down()
            conn = AsyncConnection(self, peer_name, peer_addr,
                                   self.policy_for(peer_name),
                                   self.pool.pick())
            self.conns[peer_name] = conn
            self._conns_by_addr[peer_addr] = conn
        conn.worker.call(conn._start_out)
        return conn

    def send_message(self, msg: Message, peer_name: str,
                     peer_addr: EntityAddr) -> None:
        if peer_addr == self.addr and peer_name == self.name:
            msg.src = self.name
            self.home.call(self._fast_dispatch_local, msg)
            return
        self.get_connection(peer_name, peer_addr).send_message(msg)

    def _fast_dispatch_local(self, msg: Message) -> None:
        conn = self.conns.get(self.name)
        if conn is None:
            conn = AsyncConnection(self, self.name, self.addr,
                                   Policy.lossless_peer(), self.home)
            self.conns[self.name] = conn
        self._deliver(conn, msg)

    def _conn_reset(self, conn) -> None:
        conn._close()
        super()._conn_reset(conn)

    # -- incoming ------------------------------------------------------

    def _on_accept_ready(self, mask: int) -> None:
        ls = self._listener
        if ls is None:
            return
        while True:
            try:
                raw, _peer = ls.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            if self._stopped:
                raw.close()
                continue
            worker = self.pool.pick()
            worker.call(self._begin_accept, worker, raw)

    def _begin_accept(self, worker, raw: socket.socket) -> None:
        if self._stopped:
            raw.close()
            return
        sock = _Sock(worker, raw,
                     on_resume=lambda: self.perf.inc(
                         "partial_write_resumes"))
        with self._conn_lock:
            self._accepting.add(sock)

        def _exit(result, exc):
            with self._conn_lock:
                self._accepting.discard(sock)
            if exc is not None or result is None:
                if exc is not None and not isinstance(
                        exc, (_BadBanner, ConnectionError, OSError)):
                    self.log.error("accept handshake died: %r", exc)
                sock.close()
                return
            self._finish_accept(sock, *result)
        _drive(sock, _accept_hs_gen(self, sock), _exit)

    def _finish_accept(self, sock: _Sock, peer_name: str,
                       peer_addr: EntityAddr, nonce: int, skey) -> None:
        if self._stopped:
            sock.close()
            return
        if faults.get().partitioned(peer_name, self.name):
            # one-way partitions block the peer->us direction here
            sock.close()
            return
        with self._conn_lock:
            conn = self.conns.get(peer_name)
            if conn is None or conn._closed:
                conn = AsyncConnection(self, peer_name, peer_addr,
                                       self.policy_for(peer_name),
                                       sock.worker)
                self.conns[peer_name] = conn
        if conn.worker is sock.worker:
            conn._attach_accepted(sock, skey, nonce, peer_addr)
        else:
            sock.migrate(conn.worker,
                         lambda: conn._attach_accepted(
                             sock, skey, nonce, peer_addr))
