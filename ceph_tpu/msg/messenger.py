"""Async messenger: one event-loop thread per daemon, typed dispatch.

Semantics from the reference (msg/Messenger.h, msg/async/):
  * a Messenger binds a listening address and owns Connections;
  * per-peer-class Policy: lossy (client links — drop on failure, peer
    re-establishes) vs lossless (cluster links — auto-reconnect with
    backoff and resend of unacked queued messages, preserving order);
  * Dispatchers get ms_dispatch(conn, msg) on a dispatch thread;
  * sending to your own address short-circuits through loopback fast
    dispatch (no sockets), as OSD self-sends do (osd/ECBackend.cc:1842);
  * fault injection goes through the central FaultSet registry
    (ceph_tpu/utils/faults.py): partitions (symmetric or one-way),
    targeted drops/delays, and socket kills — the legacy
    ms_inject_socket_failures / ms_inject_delay_* knobs still work but
    their randomness now flows through the FaultSet's seeded streams.

Handshake: on connect, the client sends a banner with its entity name +
reply address; the acceptor registers the connection under that name for
reply routing and answers with the highest seq it has received on that
link (in_seq), so the connector resends only frames the peer actually
missed (the reference AsyncMessenger's connect/accept seq exchange,
msg/async/AsyncConnection.cc) — without this, lost acks at socket close
make every reconnect replay the whole backlog and delivery can livelock
under repeated failures.

Auth (auth_cluster_required=cephx): after the banner, both ends run the
cephx-lite challenge-response (ceph_tpu/auth/cephx.py) — the acceptor
proves it holds the connector's keyring secret and vice versa — and
derive a per-socket session key that signs every subsequent frame
(CephxSessionHandler semantics).  A peer without the secret cannot
complete the handshake and a tampered frame fails its signature.
"""

from __future__ import annotations

import asyncio
import random
import struct
import threading
import time
from dataclasses import dataclass
from typing import Callable

from ..auth import cephx
from ..utils import faults
from ..utils.dout import DoutLogger
from .message import Message


class AuthError(Exception):
    pass

_BANNER = struct.Struct("<4sQII")    # magic, nonce, name len, addr-blob len
_BANNER_REPLY = struct.Struct("<4sQ")  # magic, acceptor's in_seq
_ADDR = struct.Struct("<HI")         # host length, port
BANNER_MAGIC = b"CTB2"


def _pack_addr(addr: "EntityAddr") -> bytes:
    host = addr[0].encode("utf-8")
    return _ADDR.pack(len(host), addr[1]) + host


def _unpack_addr(blob: bytes) -> "EntityAddr":
    if len(blob) < _ADDR.size:
        raise ValueError("short addr blob")
    hlen, port = _ADDR.unpack_from(blob)
    if len(blob) != _ADDR.size + hlen:
        raise ValueError("bad addr blob")
    return (blob[_ADDR.size:].decode("utf-8"), port)

EntityAddr = tuple[str, int]         # (host, port)


@dataclass
class Policy:
    lossy: bool = False
    server: bool = False             # accept-only side of lossy links

    @staticmethod
    def lossy_client() -> "Policy":
        return Policy(lossy=True)

    @staticmethod
    def stateless_server() -> "Policy":
        return Policy(lossy=True, server=True)

    @staticmethod
    def lossless_peer() -> "Policy":
        return Policy(lossy=False)


class Dispatcher:
    """Interface daemons implement to receive messages."""

    def ms_dispatch(self, conn: "Connection", msg: Message) -> bool:
        """Return True if handled."""
        raise NotImplementedError

    def ms_handle_reset(self, conn: "Connection") -> None:
        """Peer connection dropped (lossy) or gave up (lossless)."""


class Connection:
    """One peer link; owns an ordered send queue."""

    def __init__(self, msgr: "Messenger", peer_name: str,
                 peer_addr: EntityAddr | None, policy: Policy):
        self.msgr = msgr
        self.peer_name = peer_name          # may be "" until handshake
        self.peer_addr = peer_addr
        self.policy = policy
        # incarnation nonce is PER CONNECTION, not per messenger: a
        # lossy conn recreated by the same process restarts its seq
        # space at 1, and under the old (process-wide) nonce the
        # acceptor kept its stale in_seq and silently dropped every
        # fresh frame as a duplicate (the reference tracks this with
        # connect_seq/global_seq per attempt)
        self.nonce = random.getrandbits(63) or 1
        self.peer_nonce = 0                 # peer incarnation (acceptor side)
        self.out_seq = 0
        self.in_seq = 0
        # frames are IOVECS (lists of buffers from Message.encode_iov):
        # payload segments stay views onto the sender's memory until
        # the gather write — resends reuse the same views
        self._queue: list[tuple[int, list]] = []    # (seq, iovec) unsent
        self._sent: list[tuple[int, list]] = []     # sent, not yet acked
        self._writer: asyncio.StreamWriter | None = None
        self._closed = False
        self._send_event = asyncio.Event()
        self._task: asyncio.Task | None = None
        self.last_active = time.time()
        msgr.perf.inc("open_connections")
        self._counted = True

    # -- sending (thread-safe entry) ---------------------------------------

    def send_message(self, msg: Message) -> None:
        self.msgr._loop_call(self._queue_msg, msg)

    def _queue_msg(self, msg: Message) -> None:
        if self._closed:
            return
        msg.src = self.msgr.name
        self.out_seq += 1
        frame = msg.encode_iov(self.out_seq)
        self.msgr.perf.inc("msg_send")
        self.msgr.perf.inc("bytes_send", sum(len(b) for b in frame))
        self._queue.append((self.out_seq, frame))
        self._send_event.set()
        self.msgr._start_conn(self)   # acceptor-created conns lazily
                                      # grow a writer on first send

    def _handle_ack(self, seq: int) -> None:
        self._sent = [(s, f) for s, f in self._sent if s > seq]

    def _requeue_sent(self, peer_in_seq: int) -> None:
        """Reconnected: unacked frames the peer has not seen go back to
        the front in seq order; anything at or below the peer's in_seq
        was delivered (its ack was lost) and is dropped."""
        if self._sent:
            self._queue[:0] = self._sent
            self._sent = []
        if peer_in_seq:
            self._queue = [(s, f) for s, f in self._queue
                           if s > peer_in_seq]

    def mark_down(self) -> None:
        self.msgr._loop_call(self._close)

    def _close(self) -> None:
        self._closed = True
        self._send_event.set()
        if self._counted:
            self._counted = False
            self.msgr.perf.dec("open_connections")
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass
            self._writer = None

    def __repr__(self):
        return (f"Connection({self.msgr.name}->{self.peer_name}"
                f"@{self.peer_addr})")


class Messenger:
    def __init__(self, name: str, conf=None):
        from ..utils.config import Config
        self.name = name                     # entity name "osd.3"
        self.conf = conf or Config()
        self.addr: EntityAddr | None = None
        self.dispatchers: list[Dispatcher] = []
        self.conns: dict[str, Connection] = {}      # peer name -> conn
        self._conns_by_addr: dict[EntityAddr, Connection] = {}
        self.log = DoutLogger("ms", name)
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._server: asyncio.AbstractServer | None = None
        self._started = threading.Event()
        self._default_policy = Policy.lossless_peer()
        self._policies: dict[str, Policy] = {}      # peer type -> policy

        # perf counters (common/perf_counters.h msgr set) — registered
        # into the owning daemon's collection via register_perf()
        from ..utils.perf_counters import PerfCountersBuilder
        self.perf = (PerfCountersBuilder(f"msgr.{name}")
                     .add_u64_counter("msg_send")
                     .add_u64_counter("msg_recv")
                     .add_u64_counter("bytes_send")
                     .add_u64_counter("bytes_recv")
                     .add_u64_counter("reconnects")
                     .add_u64_counter("auth_failures")
                     .add_u64_counter("auth_ticket_accepts")
                     .add_u64_counter("auth_secret_accepts")
                     # event-loop plane (shared schema across stacks:
                     # the blocking stack reports 1 worker and never
                     # sees a partial write — asyncio hides them)
                     .add_u64("event_workers")
                     .add_u64("open_connections")
                     .add_u64_counter("event_wakeups")
                     .add_u64_counter("partial_write_resumes")
                     .add_u64_counter("accepts")
                     .create_perf_counters())

        # auth: resolved once; _key_for() answers per-entity lookups
        self.auth_mode = str(getattr(self.conf, "auth_cluster_required",
                                     "none") or "none")
        self._keyring = None
        self.auth_key: bytes | None = None
        if self.auth_mode == "cephx":
            import base64
            from ..auth import KeyRing
            key_b64 = str(getattr(self.conf, "key", "") or "")
            ring_path = str(getattr(self.conf, "keyring", "") or "")
            if ring_path:
                self._keyring = KeyRing.from_file(ring_path)
            if key_b64:
                self.auth_key = base64.b64decode(key_b64)
            elif self._keyring is not None:
                self.auth_key = self._keyring.get(self.name)
            if self.auth_key is None:
                raise ValueError(
                    f"auth_cluster_required=cephx but no key for "
                    f"{self.name} (set `key` or `keyring`)")
        # ticket auth (CephxProtocol TGS indirection): a connector
        # with a service ticket presents the sealed blob instead of
        # proving the static keyring secret; an acceptor holding the
        # service's ROTATING secrets (fetched from the mon) redeems
        # it.  Both are provisioned by MonClient.enable_service_auth.
        self.ticket_provider = None        # callable(service)->dict
        self.rotating_keys: dict[int, bytes] = {}
        self.ticket_clock = time.time      # expiry reference

    def _key_for(self, entity: str) -> bytes | None:
        """The secret we expect `entity` to prove knowledge of.

        With a keyring configured, an entity absent from it (and no
        "*" wildcard) is REJECTED — falling back to our own key would
        let any same-key holder impersonate revoked entities.  The
        bare `key=` mode is explicitly the shared-secret deployment.
        """
        if self._keyring is not None:
            return self._keyring.get(entity)
        return self.auth_key

    # -- cephx-lite handshake (per socket) ---------------------------------

    async def _auth_connect(self, peer_name: str, reader,
                            writer) -> bytes:
        """Connector side.  With a service ticket for the peer's
        class, present the sealed blob (mode 2, the TGS path) and
        prove the CONNECTION secret it carries; else run the static
        shared-secret exchange (mode 1)."""
        service = peer_name.split(".", 1)[0] if peer_name else ""
        ticket = (self.ticket_provider(service)
                  if self.ticket_provider else None)
        if ticket is not None:
            blob = ticket["blob"]
            key = ticket["key"]
            cn = cephx.make_nonce()
            writer.write(b"\x02" + len(blob).to_bytes(2, "big")
                         + blob + cn)
        else:
            key = self.auth_key
            cn = cephx.make_nonce()
            writer.write(b"\x01" + cn)
        blob2 = await reader.readexactly(cephx.NONCE_LEN + cephx.PROOF_LEN)
        sn, proof_s = blob2[:cephx.NONCE_LEN], blob2[cephx.NONCE_LEN:]
        if proof_s != cephx.proof(key, cn, sn, b"srv"):
            raise AuthError("server proof mismatch")
        writer.write(cephx.proof(key, cn, sn, b"cli"))
        return cephx.session_key(key, cn, sn)

    async def _auth_accept(self, peer_name: str, reader, writer) -> bytes:
        """Acceptor side: redeem a ticket blob against our rotating
        service secrets (mode 2), or prove/verify the peer's static
        secret (mode 1).  A peer whose entity has no keyring entry is
        rejected."""
        mode = await reader.readexactly(1)
        if mode == b"\x02":
            ln = int.from_bytes(await reader.readexactly(2), "big")
            blob = await reader.readexactly(ln)
            info = None
            for secret in self.rotating_keys.values():
                payload = cephx.unseal(secret, blob)
                if payload is not None:
                    from ..utils import denc as _denc
                    info = _denc.loads(payload)
                    break
            if info is None:
                raise AuthError(
                    f"ticket from {peer_name} matches no rotating key")
            if info.get("client") != peer_name:
                raise AuthError(
                    f"ticket for {info.get('client')!r} presented by "
                    f"{peer_name}")
            if float(info.get("expires", 0)) < self.ticket_clock():
                raise AuthError(f"expired ticket from {peer_name}")
            key = info["key"]
            self.perf.inc("auth_ticket_accepts")
        else:
            key = self._key_for(peer_name)
            if key is None:
                raise AuthError(f"no key for {peer_name}")
            self.perf.inc("auth_secret_accepts")
        cn = await reader.readexactly(cephx.NONCE_LEN)
        sn = cephx.make_nonce()
        writer.write(sn + cephx.proof(key, cn, sn, b"srv"))
        proof_c = await reader.readexactly(cephx.PROOF_LEN)
        if proof_c != cephx.proof(key, cn, sn, b"cli"):
            raise AuthError(f"bad client proof from {peer_name}")
        return cephx.session_key(key, cn, sn)

    # -- lifecycle ---------------------------------------------------------

    def bind(self, addr: EntityAddr) -> None:
        self.addr = addr

    def set_policy(self, peer_type: str, policy: Policy) -> None:
        """peer_type: entity prefix, e.g. 'client', 'osd', 'mon'."""
        self._policies[peer_type] = policy

    def set_default_policy(self, policy: Policy) -> None:
        self._default_policy = policy

    def policy_for(self, peer_name: str) -> Policy:
        ptype = peer_name.split(".", 1)[0] if peer_name else ""
        return self._policies.get(ptype, self._default_policy)

    def add_dispatcher_head(self, d: Dispatcher) -> None:
        self.dispatchers.insert(0, d)

    def add_dispatcher_tail(self, d: Dispatcher) -> None:
        self.dispatchers.append(d)

    def start(self) -> None:
        self.perf.set("event_workers", 1)     # this stack: one loop thread
        self._thread = threading.Thread(target=self._run,
                                        name=f"ms-{self.name}", daemon=True)
        self._thread.start()
        if not self._started.wait(10):
            raise RuntimeError(f"messenger {self.name} failed to start")

    def _run(self) -> None:
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        if self.addr is not None:
            self._loop.run_until_complete(self._bind_server())
        self._started.set()
        try:
            self._loop.run_forever()
        finally:
            pending = asyncio.all_tasks(self._loop)
            for t in pending:
                t.cancel()
            try:
                self._loop.run_until_complete(
                    asyncio.gather(*pending, return_exceptions=True))
            except Exception:
                pass
            self._loop.close()

    async def _bind_server(self) -> None:
        host, port = self.addr
        self._server = await asyncio.start_server(self._accept, host, port)
        if port == 0:     # ephemeral: learn the real port
            sock = self._server.sockets[0]
            self.addr = (host, sock.getsockname()[1])

    def shutdown(self) -> None:
        if self._loop is None:
            return

        def _stop():
            for conn in list(self.conns.values()):
                conn._close()
            if self._server is not None:
                self._server.close()
            self._loop.stop()

        try:
            self._loop.call_soon_threadsafe(_stop)
        except RuntimeError:
            return
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None

    # -- loop helpers ------------------------------------------------------

    def _loop_call(self, fn: Callable, *args) -> None:
        if self._loop is None:
            raise RuntimeError(f"messenger {self.name} not started")
        if threading.current_thread() is not self._thread:
            self.perf.inc("event_wakeups")    # cross-thread loop handoff
        self._loop.call_soon_threadsafe(fn, *args)

    def call_later(self, delay: float, fn: Callable, *args):
        """Cancelable timer on the messenger loop — the async stack has
        the same surface, so components (e.g. the monc subscription
        renewer) can run periodic work without a thread of their own."""
        state = {"cancelled": False, "timer": None}

        def _arm():
            if not state["cancelled"]:
                state["timer"] = self._loop.call_later(delay, _fire)

        def _fire():
            if not state["cancelled"]:
                fn(*args)

        class _Handle:
            def cancel(self_h):
                state["cancelled"] = True
                t = state["timer"]
                if t is not None:
                    try:
                        self._loop.call_soon_threadsafe(t.cancel)
                    except RuntimeError:
                        pass
        self._loop_call(_arm)
        return _Handle()

    def event_stats(self) -> dict:
        """The msgr_event perf-dump block (worker model overview)."""
        return {"type": "blocking", "workers": 1,
                "connections": len(self.conns), "per_worker": []}

    # -- outgoing ----------------------------------------------------------

    def get_connection(self, peer_name: str,
                       peer_addr: EntityAddr) -> Connection:
        """Find or create the (single) connection to a peer."""
        conn = self.conns.get(peer_name)
        if conn is not None and not conn._closed:
            if conn.peer_addr == peer_addr:
                return conn
            # the peer rebooted at a new address (daemons bind
            # ephemeral ports): the old lossless session would
            # reconnect-loop against a dead socket and strand its
            # queue — drop it and dial the new incarnation
            conn.mark_down()
        policy = self.policy_for(peer_name)
        conn = Connection(self, peer_name, peer_addr, policy)
        self.conns[peer_name] = conn
        self._conns_by_addr[peer_addr] = conn
        self._loop_call(self._start_conn, conn)
        return conn

    def send_message(self, msg: Message, peer_name: str,
                     peer_addr: EntityAddr) -> None:
        if peer_addr == self.addr and peer_name == self.name:
            # loopback fast dispatch: no sockets, no serialization
            msg.src = self.name
            self._loop_call(self._fast_dispatch_local, msg)
            return
        self.get_connection(peer_name, peer_addr).send_message(msg)

    def _fast_dispatch_local(self, msg: Message) -> None:
        conn = self.conns.get(self.name)
        if conn is None:
            conn = Connection(self, self.name, self.addr,
                              Policy.lossless_peer())
            self.conns[self.name] = conn
        self._deliver(conn, msg)

    def _start_conn(self, conn: Connection) -> None:
        if conn._task is None or conn._task.done():
            conn._task = self._loop.create_task(self._conn_writer(conn))

    # -- connection coroutines ---------------------------------------------

    async def _conn_writer(self, conn: Connection) -> None:
        backoff = float(self.conf.ms_initial_backoff)
        while not conn._closed:
            if faults.get().partitioned(self.name, conn.peer_name):
                # installed partition: the peer is unreachable.  Lossy
                # links reset (the peer re-establishes after heal);
                # lossless links poll at the INITIAL backoff without
                # growing it, so heal latency stays deterministic
                # instead of riding wherever the exponential curve got
                if conn.policy.lossy:
                    self._conn_reset(conn)
                    return
                await asyncio.sleep(float(self.conf.ms_initial_backoff))
                continue
            try:
                reader, writer = await asyncio.open_connection(
                    *conn.peer_addr)
            except OSError:
                if conn.policy.lossy:
                    self._conn_reset(conn)
                    return
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2,
                              float(self.conf.ms_max_backoff))
                continue
            # banner: our incarnation nonce + who we are + where replies
            # reach us; the acceptor answers with its in_seq for THIS
            # incarnation so we resend only what it actually missed
            name_b = self.name.encode()
            addr_b = _pack_addr(self.addr)
            writer.write(_BANNER.pack(BANNER_MAGIC, conn.nonce,
                                      len(name_b), len(addr_b))
                         + name_b + addr_b)
            try:
                # auth runs BEFORE the acceptor reveals any session
                # state (its banner reply carries in_seq)
                skey = None
                if self.auth_mode == "cephx":
                    skey = await asyncio.wait_for(
                        self._auth_connect(conn.peer_name, reader,
                                           writer),
                        timeout=float(self.conf.ms_connect_timeout))
                # bounded: a peer whose backlog accepted the TCP
                # connection but whose event loop is wedged must not
                # pin this coroutine forever
                rep = await asyncio.wait_for(
                    reader.readexactly(_BANNER_REPLY.size),
                    timeout=float(self.conf.ms_connect_timeout))
                magic, peer_in_seq = _BANNER_REPLY.unpack(rep)
                if magic != BANNER_MAGIC:
                    raise ConnectionResetError("bad banner reply")
            except (AuthError, asyncio.IncompleteReadError,
                    asyncio.TimeoutError, ConnectionError, OSError):
                writer.close()
                if conn.policy.lossy:
                    self._conn_reset(conn)
                    return
                # a wedged peer that accepts but never answers must not
                # be hammered: same exponential backoff as conn refusal
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2,
                              float(self.conf.ms_max_backoff))
                continue
            backoff = float(self.conf.ms_initial_backoff)
            conn._writer = writer
            conn._requeue_sent(peer_in_seq)
            # race reader (notices peer death via EOF) against writer:
            # either side failing tears the socket down and, for
            # lossless links, triggers reconnect + resend of unacked
            reader_t = self._loop.create_task(
                self._read_frames(conn, reader, writer, skey))
            drain_t = self._loop.create_task(
                self._drain_queue(conn, writer, skey))
            done, pending = await asyncio.wait(
                {reader_t, drain_t}, return_when=asyncio.FIRST_COMPLETED)
            for t in pending:
                t.cancel()
            try:
                writer.close()
            except Exception:
                pass
            conn._writer = None
            unexpected = False
            for t in done:
                exc = t.exception()
                if exc is not None and not isinstance(
                        exc, (ConnectionError, OSError)):
                    # never let the writer task die on an unexpected
                    # error: the conn would strand its queue until the
                    # next send restarts it — log and reconnect
                    self.log.error("conn loop to %s error: %r",
                                   conn.peer_name, exc)
                    unexpected = True
            if conn._closed:
                return
            if unexpected:
                # a deterministic error would otherwise spin a tight
                # reconnect/handshake storm (backoff was reset after
                # the successful banner)
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2,
                              float(self.conf.ms_max_backoff))
            if conn.policy.lossy:
                self._conn_reset(conn)
                return
            self.perf.inc("reconnects")
            conn._send_event.set()
            continue   # lossless: reconnect, resend unacked

    async def _drain_queue(self, conn: Connection,
                           writer: asyncio.StreamWriter,
                           skey: bytes | None = None) -> None:
        while not conn._closed:
            while conn._queue:
                seq, frame = conn._queue[0]
                fs = faults.get()
                if fs.partitioned(self.name, conn.peer_name):
                    # partition landed mid-connection: tear the socket
                    # down; the reconnect loop blocks until heal
                    writer.close()
                    raise ConnectionResetError("partitioned")
                if fs.should_kill_socket(
                        self.name, conn.peer_name,
                        int(self.conf.ms_inject_socket_failures)):
                    self.log.debug("injecting socket failure to %s",
                                   conn.peer_name)
                    writer.close()
                    raise ConnectionResetError("injected")
                d = fs.send_delay(self.name, conn.peer_name)
                if d > 0:
                    await asyncio.sleep(d)
                if fs.should_drop(self.name, conn.peer_name):
                    # modeled message loss: the frame is never written.
                    # Lossless links keep it in _sent so the NEXT
                    # reconnect resends it (unless the peer's in_seq
                    # moved past it); higher layers' retries own
                    # end-to-end recovery, as with real packet loss.
                    conn._queue.pop(0)
                    if not conn.policy.lossy:
                        conn._sent.append((seq, frame))
                    continue
                # sign at write time, store UNSIGNED: a resent frame
                # must be re-signed with the new socket's session key.
                # The frame is an iovec — header, seg table, payload,
                # data segments — gather-written as-is; the signature
                # folds the buffers without joining them.
                if skey is None:
                    writer.writelines(frame)
                else:
                    writer.writelines(
                        frame + [cephx.sign_iov(skey, [b"C", *frame])])
                await writer.drain()
                conn._queue.pop(0)
                if not conn.policy.lossy:
                    # lossless: keep until the peer acks the seq
                    conn._sent.append((seq, frame))
                conn.last_active = time.time()
            conn._send_event.clear()
            await conn._send_event.wait()

    def _conn_reset(self, conn: Connection) -> None:
        conn._closed = True
        if conn._counted:
            conn._counted = False
            self.perf.dec("open_connections")
        self.conns.pop(conn.peer_name, None)
        if conn.peer_addr is not None:
            self._conns_by_addr.pop(conn.peer_addr, None)
        for d in self.dispatchers:
            try:
                d.ms_handle_reset(conn)
            except Exception:
                self.log.error("dispatcher reset handler failed")

    # -- incoming ----------------------------------------------------------

    async def _accept(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            hdr = await reader.readexactly(_BANNER.size)
            magic, nonce, nlen, alen = _BANNER.unpack(hdr)
            if magic != BANNER_MAGIC:
                writer.close()
                return
            peer_name = (await reader.readexactly(nlen)).decode()
            peer_addr = _unpack_addr(await reader.readexactly(alen))
        except (asyncio.IncompleteReadError, ConnectionError, OSError,
                ValueError, UnicodeDecodeError):
            writer.close()
            return
        # authenticate BEFORE registering the connection or mutating
        # any session state — an unauthenticated banner must not be
        # able to reset a live peer's in_seq/address or learn in_seq
        skey = None
        if self.auth_mode == "cephx":
            try:
                skey = await asyncio.wait_for(
                    self._auth_accept(peer_name, reader, writer),
                    timeout=float(self.conf.ms_connect_timeout))
            except (AuthError, asyncio.IncompleteReadError,
                    asyncio.TimeoutError, ConnectionError, OSError) as e:
                self.perf.inc("auth_failures")
                self.log.warn("rejecting %s: auth failed (%s)",
                              peer_name, e)
                writer.close()
                return
        if faults.get().partitioned(peer_name, self.name):
            # one-way partitions block the peer->us direction here;
            # our own sends to the peer are gated on the connect side
            writer.close()
            return
        conn = self.conns.get(peer_name)
        if conn is None or conn._closed:
            conn = Connection(self, peer_name, peer_addr,
                              self.policy_for(peer_name))
            self.conns[peer_name] = conn
        if conn.peer_nonce != nonce:
            # new peer incarnation (restarted daemon): its seq space
            # restarts at 0, so a stale in_seq reply would make it drop
            # its first frames; and its reply address may have moved
            conn.peer_nonce = nonce
            conn.in_seq = 0
            conn.peer_addr = peer_addr
        try:
            writer.write(_BANNER_REPLY.pack(BANNER_MAGIC, conn.in_seq))
        except (ConnectionError, OSError):
            writer.close()
            return
        self.perf.inc("accepts")
        try:
            await self._read_frames(conn, reader, writer, skey,
                                    accepted=True)
        except Exception as e:
            # an unexpected error must not ABANDON the socket: leaving
            # it open-but-unread lets the peer write into a black hole
            # forever (its frames sit unacked while it sees a healthy
            # connection) — close it so the peer reconnects + resends
            self.log.error("accept loop for %s died: %r",
                           conn.peer_name, e)
        finally:
            try:
                writer.close()
            except Exception:
                pass

    ACK_TYPE = 1

    def _ack_frame(self, seq: int) -> bytes:
        from .message import _HDR, MAGIC
        return _HDR.pack(MAGIC, self.ACK_TYPE, 0, seq)

    async def _read_frames(self, conn: Connection,
                           reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter | None,
                           skey: bytes | None = None,
                           accepted: bool = False) -> None:
        # Signatures are DIRECTION-BOUND: the connector signs under
        # "C", the acceptor under "S" — without the label a MITM could
        # reflect a side's own signed frame back at it and it would
        # verify (same session key both ways).
        recv_label = b"C" if accepted else b"S"
        send_label = b"S" if accepted else b"C"
        hdr_size = Message.header_size()
        try:
            while not conn._closed:
                hdr = await reader.readexactly(hdr_size)
                type_id, plen, seq, has_segs = \
                    Message.parse_header_any(hdr)
                body = await reader.readexactly(plen)
                segments: list[bytes] = []
                if has_segs:
                    # CTM2: the body is <seg table><denc payload>; the
                    # data segments follow and scatter-read one by one
                    # (never joined with the payload)
                    seg_lens, payload = Message.parse_seg_table(body)
                    for n in seg_lens:
                        segments.append(await reader.readexactly(n))
                else:
                    payload = body
                nbytes = hdr_size + plen + sum(len(s) for s in segments)
                self.perf.inc("bytes_recv", nbytes)
                if skey is not None:
                    sig = await reader.readexactly(cephx.SIG_LEN)
                    if not cephx.check_iov(
                            skey, [recv_label, hdr, body, *segments],
                            sig):
                        self.log.warn("bad frame signature from %s, "
                                      "dropping connection",
                                      conn.peer_name)
                        raise ConnectionResetError("bad signature")
                fs = faults.get()
                if fs.partitioned(conn.peer_name, self.name):
                    # a partition installed mid-connection must stop
                    # delivery too — and BEFORE the ack/in_seq
                    # bookkeeping, so the frame is not acknowledged as
                    # delivered and a lossless peer resends it after
                    # the heal
                    raise ConnectionResetError("partitioned")
                if type_id == self.ACK_TYPE:
                    conn._handle_ack(seq)
                    continue
                if writer is not None:
                    try:
                        ack = self._ack_frame(seq)
                        if skey is not None:
                            ack = ack + cephx.sign(skey,
                                                   send_label + ack)
                        writer.write(ack)
                    except (ConnectionError, OSError):
                        pass
                if seq <= conn.in_seq:
                    continue            # dup after reconnect
                conn.in_seq = seq
                try:
                    msg = Message.decode(type_id, seq, payload, segments)
                except ValueError:
                    # corrupt/hostile frame: data-only decode failed;
                    # skip it (resend would fail identically) but keep
                    # the link and subsequent frames alive
                    self.log.error(
                        "undecodable frame type=%d seq=%d from %s",
                        type_id, seq, conn.peer_name)
                    continue
                d = fs.recv_delay(
                    conn.peer_name, self.name,
                    float(self.conf.ms_inject_delay_probability),
                    float(self.conf.ms_inject_delay_max))
                if d > 0:
                    await asyncio.sleep(d)
                self._deliver(conn, msg)
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass

    def _deliver(self, conn: Connection, msg: Message) -> None:
        self.perf.inc("msg_recv")
        for d in self.dispatchers:
            try:
                if d.ms_dispatch(conn, msg):
                    return
            except Exception as e:
                from ..utils.faults import CrashPoint
                if isinstance(e, CrashPoint):
                    # a fired crash point unwinds through dispatch by
                    # design: the daemon is aborting, the op dies
                    # silently (never acked, never nacked)
                    return
                import traceback
                traceback.print_exc()
                self.log.error("dispatch of %r failed", msg)
                return
        self.log.warn("unhandled message %r from %s", msg, conn.peer_name)


def create_messenger(name: str, conf=None) -> Messenger:
    """Messenger::create analog: ms_type selects the serving stack.

    `blocking` is the original one-loop-thread-per-messenger stack;
    `async` multiplexes every connection in the process onto the shared
    `ms_async_op_threads` epoll worker pool (msg/async_messenger.py).
    Both speak the identical wire protocol."""
    from ..utils.config import Config
    conf = conf or Config()
    ms_type = str(getattr(conf, "ms_type", "blocking") or "blocking")
    if ms_type == "async":
        from .async_messenger import AsyncMessenger
        return AsyncMessenger(name, conf)
    if ms_type != "blocking":
        raise ValueError(f"unknown ms_type {ms_type!r}")
    return Messenger(name, conf)
