"""Shared epoll event-loop worker pool for the async messenger.

The reference AsyncMessenger (msg/async/Stack.h, Event.cc) runs a fixed
pool of `ms_async_op_threads` workers, each owning one epoll loop; every
connection in the process is multiplexed onto one of those loops, so the
thread count is bounded by the pool size, not by connections or
messenger instances.  This module is that pool: selectors-based event
loops (EpollSelector on Linux) with

  * a wakeup socketpair per worker (EventCenter::wakeup) so foreign
    threads — op shards posting replies, clients queueing sends — can
    hand work to the loop;
  * a monotonic timer heap (EventCenter::create_time_event) for
    backoff, handshake timeouts and injected delays;
  * per-worker stats (registered sockets, loop wakeups) surfaced
    through `perf dump`'s msgr_event block.

Workers are process-wide daemon threads created on first use and keyed
by pool size; they are never torn down (messengers come and go, the
pool persists — shutdown hygiene lives at the messenger/connection
layer, which closes its own sockets deterministically).
"""

from __future__ import annotations

import heapq
import selectors
import socket
import threading
import time
from collections import deque
from typing import Callable

from ..utils.dout import DoutLogger


class TimerHandle:
    """Cancelable handle for EventWorker.call_later."""

    __slots__ = ("fn", "args", "cancelled")

    def __init__(self, fn: Callable, args: tuple):
        self.fn = fn
        self.args = args
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True


class EventWorker(threading.Thread):
    """One epoll loop; all fd callbacks and timers run on this thread.

    Selector mutations (register/modify/unregister) are NOT thread-safe
    against select(), so every socket operation is funneled onto the
    loop via call()/call_later(); only those two entry points may be
    used from foreign threads.
    """

    def __init__(self, index: int):
        super().__init__(name=f"ms-async-{index}", daemon=True)
        self.index = index
        self.sel = selectors.DefaultSelector()
        self.log = DoutLogger("ms", f"async-worker.{index}")
        self._lock = threading.Lock()
        self._pending: deque[tuple[Callable, tuple]] = deque()
        self._timers: list[tuple[float, int, TimerHandle]] = []
        self._timer_seq = 0
        self._stop = False
        # socks: _Sock instances currently registered on this loop
        # (connection balancing + the per-worker perf-dump view);
        # wakeups: loop iterations that found fd events to service
        self.stats = {"socks": 0, "wakeups": 0}
        # wakeup pipe: any thread writes a byte to pop the loop out of
        # select() after posting to _pending
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self.sel.register(self._wake_r, selectors.EVENT_READ, None)

    # -- cross-thread entry points -------------------------------------

    def call(self, fn: Callable, *args) -> None:
        """Run fn(*args) on the loop thread (soonest iteration)."""
        with self._lock:
            self._pending.append((fn, args))
        if threading.current_thread() is not self:
            self.wake()

    def call_later(self, delay: float, fn: Callable, *args) -> TimerHandle:
        """Run fn(*args) on the loop thread after `delay` seconds."""
        h = TimerHandle(fn, args)
        if threading.current_thread() is self:
            self._arm(delay, h)
        else:
            self.call(self._arm, delay, h)
        return h

    def wake(self) -> None:
        try:
            self._wake_w.send(b"\0")
        except (BlockingIOError, OSError):
            pass                      # pipe full: loop is waking anyway

    # -- loop internals ------------------------------------------------

    def _arm(self, delay: float, h: TimerHandle) -> None:
        self._timer_seq += 1
        heapq.heappush(self._timers,
                       (time.monotonic() + max(0.0, delay),
                        self._timer_seq, h))

    def _sel_set(self, fileobj, mask: int, cb) -> None:
        """Register/modify/unregister (mask=0) in one idempotent call."""
        try:
            registered = self.sel.get_key(fileobj)
        except (KeyError, ValueError):
            registered = None
        if mask == 0:
            if registered is not None:
                self.sel.unregister(fileobj)
        elif registered is None:
            self.sel.register(fileobj, mask, cb)
        else:
            self.sel.modify(fileobj, mask, cb)

    def run(self) -> None:
        while not self._stop:
            with self._lock:
                have_pending = bool(self._pending)
            if have_pending:
                timeout = 0.0
            elif self._timers:
                timeout = max(0.0,
                              self._timers[0][0] - time.monotonic())
            else:
                timeout = 1.0
            try:
                events = self.sel.select(timeout)
            except OSError:
                events = []
            if events:
                self.stats["wakeups"] += 1
            for key, mask in events:
                cb = key.data
                if cb is None:        # wakeup pipe: drain it
                    try:
                        while self._wake_r.recv(4096):
                            pass
                    except (BlockingIOError, OSError):
                        pass
                    continue
                try:
                    cb(mask)
                except Exception as e:
                    self.log.error("event callback failed: %r", e)
            now = time.monotonic()
            while self._timers and self._timers[0][0] <= now:
                _, _, h = heapq.heappop(self._timers)
                if h.cancelled:
                    continue
                try:
                    h.fn(*h.args)
                except Exception as e:
                    self.log.error("timer callback failed: %r", e)
            with self._lock:
                pending, self._pending = self._pending, deque()
            for fn, args in pending:
                try:
                    fn(*args)
                except Exception as e:
                    self.log.error("posted callback failed: %r", e)


class WorkerPool:
    """Fixed set of event workers; connections are placed on the least
    loaded loop at creation (PosixNetworkStack::get_worker)."""

    def __init__(self, n: int):
        self.workers = [EventWorker(i) for i in range(max(1, n))]
        for w in self.workers:
            w.start()

    def __len__(self) -> int:
        return len(self.workers)

    def pick(self) -> EventWorker:
        return min(self.workers,
                   key=lambda w: (w.stats["socks"], w.index))

    def stats(self) -> list[dict]:
        return [{"worker": w.index,
                 "open_sockets": w.stats["socks"],
                 "event_wakeups": w.stats["wakeups"]}
                for w in self.workers]


_pools: dict[int, WorkerPool] = {}
_pools_lock = threading.Lock()


def get_pool(n: int) -> WorkerPool:
    """The process-wide pool for `n` workers (created on first use)."""
    n = max(1, int(n))
    with _pools_lock:
        pool = _pools.get(n)
        if pool is None:
            pool = _pools[n] = WorkerPool(n)
        return pool
