"""Event-loop connection plane: nonblocking sockets, per-connection
protocol state machines, gather writes.

AsyncConnection is the exact peer-link analog of messenger.Connection —
same incarnation nonce, seq spaces, unsent/unacked queues, lossless
resend and reconnect-backoff semantics, the same two-socket shape (a
lazily dialed out-socket for the frames we send, plus whatever socket
the peer's connect landed on our acceptor) — but it owns no thread.
All of its I/O runs on its home EventWorker:

  * _Sock is the socket state machine: an `expect(n, cb)` read plan
    over an accumulating buffer (a short read resumes on the next
    EPOLLIN) and a FIFO gather-write queue driven by socket.sendmsg
    over the frame iovecs — Message.encode_iov ropes are written
    buffer-by-buffer, never joined; a short write keeps the remaining
    views and resumes on EPOLLOUT (`partial_write_resumes` counts
    those resumes);
  * the wire protocols (banner/auth handshakes, the frame read loop)
    are generators yielding ("read", n) / ("write", iov) /
    ("sleep", s), pumped by _drive() — the same code shape as the
    blocking stack's coroutines, so byte-level semantics stay aligned
    line for line;
  * the send path is an event-driven pump: per-frame fault checks in
    the blocking stack's exact order (partition, socket kill, send
    delay, drop), then sign-at-write and a gather write; a frame stays
    at the queue head until fully flushed, then moves to _sent until
    the peer acks it, so a socket death mid-write resends it.
"""

from __future__ import annotations

import errno
import random
import socket
import threading
import time
from typing import Callable

from ..auth import cephx
from ..utils import faults
from .message import Message
from .messenger import (AuthError, BANNER_MAGIC, Policy, _BANNER,
                        _BANNER_REPLY, _pack_addr, _unpack_addr)

_READ = 1       # selectors.EVENT_READ
_WRITE = 2      # selectors.EVENT_WRITE
_RECV_CHUNK = 65536
_IOV_MAX = 512  # conservative sendmsg iovec cap (Linux IOV_MAX is 1024)


class _Sock:
    """Nonblocking socket on one EventWorker: read plans + gather
    writes with partial resume.  Every method runs on the worker."""

    def __init__(self, worker, sock: socket.socket, *,
                 connecting: bool = False,
                 on_connect: Callable | None = None,
                 on_resume: Callable | None = None):
        self.worker = worker
        self.sock = sock
        sock.setblocking(False)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        self.closed = False
        self.on_error: Callable | None = None   # fn(exc), fired once
        self.on_connect = on_connect
        self.on_resume = on_resume              # partial write resumed
        self._connecting = connecting
        self._rbuf = bytearray()
        self._rpos = 0
        self._plans: list[tuple[int, Callable]] = []
        self._draining = False
        # write queue entries are [list-of-memoryviews, on_done]; the
        # head batch may be partially flushed (views already advanced)
        self._wq: list[list] = []
        self._flushing = False
        self._mask = 0
        worker.stats["socks"] += 1
        self._set_mask(_WRITE if connecting else _READ)

    # -- registration --------------------------------------------------

    def _set_mask(self, mask: int) -> None:
        if self.closed or mask == self._mask:
            return
        self._mask = mask
        self.worker._sel_set(self.sock, mask, self._on_event)

    def _on_event(self, mask: int) -> None:
        if self.closed:
            return
        if self._connecting:
            self._finish_connect()
            return
        if mask & _READ:
            self._on_readable()
        if not self.closed and (mask & _WRITE):
            self._on_writable()

    # -- connect -------------------------------------------------------

    def _finish_connect(self) -> None:
        err = self.sock.getsockopt(socket.SOL_SOCKET, socket.SO_ERROR)
        if err:
            self._fail(OSError(err, "connect failed"))
            return
        self._connecting = False
        self._set_mask(_READ | (_WRITE if self._wq else 0))
        cb, self.on_connect = self.on_connect, None
        if cb is not None:
            cb()
        self._flush()

    # -- reads ---------------------------------------------------------

    def expect(self, n: int, cb: Callable) -> None:
        """Plan to read exactly n bytes, then cb(bytes)."""
        self._plans.append((n, cb))
        self._drain_plans()

    def _on_readable(self) -> None:
        try:
            while True:
                chunk = self.sock.recv(_RECV_CHUNK)
                if not chunk:
                    self._fail(ConnectionResetError("peer closed"))
                    return
                self._rbuf += chunk
                if len(chunk) < _RECV_CHUNK:
                    break
        except (BlockingIOError, InterruptedError):
            pass
        except OSError as e:
            self._fail(e)
            return
        self._drain_plans()

    def _drain_plans(self) -> None:
        # the guard turns nested expect() calls (a plan callback asking
        # for the next field) into iterations of this loop instead of
        # recursion — a deep buffered backlog must not blow the stack
        if self._draining:
            return
        self._draining = True
        try:
            while (not self.closed and self._plans
                   and len(self._rbuf) - self._rpos
                   >= self._plans[0][0]):
                n, cb = self._plans.pop(0)
                data = bytes(self._rbuf[self._rpos:self._rpos + n])
                self._rpos += n
                if self._rpos > _RECV_CHUNK:
                    del self._rbuf[:self._rpos]
                    self._rpos = 0
                cb(data)
        finally:
            self._draining = False

    # -- gather writes -------------------------------------------------

    def send_iov(self, iov: list, on_done: Callable | None = None) -> None:
        """FIFO gather write; on_done fires (possibly synchronously)
        once every byte of the iovec reached the kernel."""
        if self.closed:
            return
        bufs = [memoryview(b) for b in iov if len(b)]
        if not bufs:
            if on_done is not None:
                on_done()
            return
        self._wq.append([bufs, on_done])
        self._flush()

    def _on_writable(self) -> None:
        if self._wq and self.on_resume is not None:
            self.on_resume()          # a partial write resumed by EPOLLOUT
        self._flush()

    def _flush(self) -> None:
        if self._flushing:
            return                    # re-entered from an on_done callback
        self._flushing = True
        try:
            while self._wq and not self.closed:
                bufs, on_done = self._wq[0]
                try:
                    sent = self.sock.sendmsg(bufs[:_IOV_MAX])
                except (BlockingIOError, InterruptedError):
                    sent = 0
                except OSError as e:
                    self._fail(e)
                    return
                while sent:
                    head = bufs[0]
                    if sent >= len(head):
                        sent -= len(head)
                        bufs.pop(0)
                    else:
                        bufs[0] = head[sent:]
                        sent = 0
                if bufs:
                    self._set_mask(_READ | _WRITE)
                    return
                self._wq.pop(0)
                if on_done is not None:
                    on_done()
            if not self.closed:
                self._set_mask(_READ)
        finally:
            self._flushing = False

    # -- teardown ------------------------------------------------------

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        self.worker.stats["socks"] -= 1
        try:
            self.worker._sel_set(self.sock, 0, None)
        except Exception:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
        self._plans.clear()
        self._wq.clear()

    def _fail(self, exc: BaseException) -> None:
        """Close now; emit on_error from a fresh loop iteration so a
        failure inside a protocol step never re-enters the generator
        that is currently executing."""
        if self.closed:
            return
        self.close()
        self.worker.call(self._emit_error, exc)

    def _emit_error(self, exc: BaseException) -> None:
        cb, self.on_error = self.on_error, None
        if cb is not None:
            cb(exc)

    # -- migration -----------------------------------------------------

    def migrate(self, new_worker, then: Callable) -> None:
        """Move this socket to another worker's loop (an accepted
        socket joins its connection's home loop once the peer is
        known).  Runs on the CURRENT worker; `then` fires on the new
        one."""
        self.worker._sel_set(self.sock, 0, None)
        self.worker.stats["socks"] -= 1
        mask, self._mask = self._mask, 0

        def _attach():
            self.worker = new_worker
            new_worker.stats["socks"] += 1
            if not self.closed:
                self._set_mask(mask or _READ)
            then()
        new_worker.call(_attach)


def _drive(sock: _Sock, gen, on_exit: Callable) -> None:
    """Pump a protocol generator over a _Sock.

    The generator yields ("read", n) -> resumes with the bytes,
    ("write", iov) -> resumes once flushed, ("sleep", secs) -> resumes
    after the delay.  A socket failure is thrown into the generator so
    its except/finally clauses run, exactly like a coroutine seeing
    ConnectionError.  on_exit(result, exc) fires exactly once; result
    is the generator's return value on clean exit."""
    done = False
    running = False
    queued: list = []          # resumes that arrived while gen executed
    _MISS = object()

    def finish(result, exc):
        nonlocal done
        if done:
            return
        done = True
        sock.on_error = None
        on_exit(result, exc)

    def step(value=None, exc=None):
        nonlocal running
        if done:
            return
        if running:
            # a callback fired synchronously while the generator was
            # executing (e.g. an error surfacing out of a nested write):
            # queue it for the active frame instead of re-entering
            queued.append((value, exc))
            return
        running = True
        try:
            _run(value, exc)
        finally:
            running = False

    def _run(value, exc):
        while True:
            try:
                if exc is not None:
                    req = gen.throw(exc)
                else:
                    req = gen.send(value)
            except StopIteration as s:
                finish(s.value, None)
                return
            except BaseException as e:
                finish(None, e)
                return
            if queued:
                # an error (or stray resume) landed mid-execution; it
                # supersedes the wait the generator just requested
                value, exc = queued.pop(0)
                continue
            kind = req[0]
            if kind == "read":
                # detect an expect() satisfied from already-buffered
                # bytes in this same stack frame and keep looping
                # instead of recursing into step()
                box = {"v": _MISS, "inline": True}

                def _rd(data, box=box):
                    if box["inline"]:
                        box["v"] = data
                    else:
                        step(data)
                sock.expect(req[1], _rd)
                box["inline"] = False
                if box["v"] is not _MISS:
                    value, exc = box["v"], None
                    continue
                return
            elif kind == "write":
                box = {"v": _MISS, "inline": True}

                def _wr(box=box):
                    if box["inline"]:
                        box["v"] = None
                    else:
                        step()
                sock.send_iov(req[1], on_done=_wr)
                box["inline"] = False
                if box["v"] is not _MISS:
                    value, exc = None, None
                    continue
                return
            elif kind == "sleep":
                sock.worker.call_later(req[1], step)
                return
            else:
                finish(None, RuntimeError(f"bad yield {req!r}"))
                return

    sock.on_error = lambda e: step(exc=e)
    step()


# -- wire protocol generators (the blocking stack's coroutines, same
#    order of reads/writes/checks, driven by _drive) -------------------

class _BadBanner(Exception):
    """Silent close: garbage banner or failed auth (already counted)."""


def _auth_connect_gen(msgr, peer_name: str):
    """Connector side of the cephx-lite handshake (mirrors
    Messenger._auth_connect)."""
    service = peer_name.split(".", 1)[0] if peer_name else ""
    ticket = (msgr.ticket_provider(service)
              if msgr.ticket_provider else None)
    if ticket is not None:
        blob = ticket["blob"]
        key = ticket["key"]
        cn = cephx.make_nonce()
        yield ("write", [b"\x02" + len(blob).to_bytes(2, "big")
                         + blob + cn])
    else:
        key = msgr.auth_key
        cn = cephx.make_nonce()
        yield ("write", [b"\x01" + cn])
    blob2 = yield ("read", cephx.NONCE_LEN + cephx.PROOF_LEN)
    sn, proof_s = blob2[:cephx.NONCE_LEN], blob2[cephx.NONCE_LEN:]
    if proof_s != cephx.proof(key, cn, sn, b"srv"):
        raise AuthError("server proof mismatch")
    yield ("write", [cephx.proof(key, cn, sn, b"cli")])
    return cephx.session_key(key, cn, sn)


def _auth_accept_gen(msgr, peer_name: str):
    """Acceptor side (mirrors Messenger._auth_accept): redeem a ticket
    against the rotating service secrets, or prove/verify the static
    secret."""
    mode = yield ("read", 1)
    if mode == b"\x02":
        ln = int.from_bytes((yield ("read", 2)), "big")
        blob = yield ("read", ln)
        info = None
        for secret in msgr.rotating_keys.values():
            payload = cephx.unseal(secret, blob)
            if payload is not None:
                from ..utils import denc as _denc
                info = _denc.loads(payload)
                break
        if info is None:
            raise AuthError(
                f"ticket from {peer_name} matches no rotating key")
        if info.get("client") != peer_name:
            raise AuthError(
                f"ticket for {info.get('client')!r} presented by "
                f"{peer_name}")
        if float(info.get("expires", 0)) < msgr.ticket_clock():
            raise AuthError(f"expired ticket from {peer_name}")
        key = info["key"]
        msgr.perf.inc("auth_ticket_accepts")
    else:
        key = msgr._key_for(peer_name)
        if key is None:
            raise AuthError(f"no key for {peer_name}")
        msgr.perf.inc("auth_secret_accepts")
    cn = yield ("read", cephx.NONCE_LEN)
    sn = cephx.make_nonce()
    yield ("write", [sn + cephx.proof(key, cn, sn, b"srv")])
    proof_c = yield ("read", cephx.PROOF_LEN)
    if proof_c != cephx.proof(key, cn, sn, b"cli"):
        raise AuthError(f"bad client proof from {peer_name}")
    return cephx.session_key(key, cn, sn)


def _connect_gen(msgr, conn):
    """Out-socket handshake: banner, auth, banner reply.  Returns
    (session_key, peer_in_seq)."""
    name_b = msgr.name.encode()
    addr_b = _pack_addr(msgr.addr)
    yield ("write", [_BANNER.pack(BANNER_MAGIC, conn.nonce,
                                  len(name_b), len(addr_b))
                     + name_b + addr_b])
    skey = None
    if msgr.auth_mode == "cephx":
        skey = yield from _auth_connect_gen(msgr, conn.peer_name)
    rep = yield ("read", _BANNER_REPLY.size)
    magic, peer_in_seq = _BANNER_REPLY.unpack(rep)
    if magic != BANNER_MAGIC:
        raise ConnectionResetError("bad banner reply")
    return skey, peer_in_seq


def _accept_hs_gen(msgr, sock: _Sock):
    """In-socket handshake up to (but excluding) conn registration:
    banner parse + auth.  Returns (peer_name, peer_addr, nonce, skey);
    raises _BadBanner for a silent close."""
    hdr = yield ("read", _BANNER.size)
    magic, nonce, nlen, alen = _BANNER.unpack(hdr)
    if magic != BANNER_MAGIC:
        raise _BadBanner()
    try:
        peer_name = (yield ("read", nlen)).decode()
        peer_addr = _unpack_addr((yield ("read", alen)))
    except (ValueError, UnicodeDecodeError):
        raise _BadBanner()
    skey = None
    if msgr.auth_mode == "cephx":
        # authenticate BEFORE any session state is revealed or mutated
        # (the banner reply carries in_seq); bound it like the blocking
        # stack's wait_for
        tmo = sock.worker.call_later(
            float(msgr.conf.ms_connect_timeout),
            lambda: sock._fail(ConnectionResetError("auth timeout")))
        try:
            skey = yield from _auth_accept_gen(msgr, peer_name)
        except (AuthError, ConnectionError, OSError) as e:
            msgr.perf.inc("auth_failures")
            msgr.log.warn("rejecting %s: auth failed (%s)",
                          peer_name, e)
            raise _BadBanner()
        finally:
            tmo.cancel()
    return peer_name, peer_addr, nonce, skey


def _frames_gen(msgr, conn, sock: _Sock, skey, accepted: bool):
    """The frame read loop — field-for-field the blocking stack's
    _read_frames: header, body, scatter-read segments, signature
    check, partition gate, ack handling, dup suppression, decode,
    injected delay, deliver."""
    recv_label = b"C" if accepted else b"S"
    send_label = b"S" if accepted else b"C"
    hdr_size = Message.header_size()
    while not conn._closed:
        hdr = yield ("read", hdr_size)
        type_id, plen, seq, has_segs = Message.parse_header_any(hdr)
        body = yield ("read", plen)
        segments: list[bytes] = []
        if has_segs:
            seg_lens, payload = Message.parse_seg_table(body)
            for n in seg_lens:
                segments.append((yield ("read", n)))
        else:
            payload = body
        nbytes = hdr_size + plen + sum(len(s) for s in segments)
        msgr.perf.inc("bytes_recv", nbytes)
        if skey is not None:
            sig = yield ("read", cephx.SIG_LEN)
            if not cephx.check_iov(
                    skey, [recv_label, hdr, body, *segments], sig):
                msgr.log.warn("bad frame signature from %s, dropping "
                              "connection", conn.peer_name)
                raise ConnectionResetError("bad signature")
        fs = faults.get()
        if fs.partitioned(conn.peer_name, msgr.name):
            raise ConnectionResetError("partitioned")
        if type_id == msgr.ACK_TYPE:
            conn._handle_ack(seq)
            continue
        ack = msgr._ack_frame(seq)
        if skey is not None:
            ack = ack + cephx.sign(skey, send_label + ack)
        sock.send_iov([ack])          # fire and forget, like writer.write
        if seq <= conn.in_seq:
            continue                  # dup after reconnect
        conn.in_seq = seq
        try:
            msg = Message.decode(type_id, seq, payload, segments)
        except ValueError:
            msgr.log.error("undecodable frame type=%d seq=%d from %s",
                           type_id, seq, conn.peer_name)
            continue
        d = fs.recv_delay(
            conn.peer_name, msgr.name,
            float(msgr.conf.ms_inject_delay_probability),
            float(msgr.conf.ms_inject_delay_max))
        if d > 0:
            yield ("sleep", d)
        msgr._deliver(conn, msg)


class AsyncConnection:
    """One peer link on the event-loop stack; all state mutations run
    on self.worker (its home EventWorker)."""

    def __init__(self, msgr, peer_name: str, peer_addr, policy: Policy,
                 worker):
        self.msgr = msgr
        self.peer_name = peer_name
        self.peer_addr = peer_addr
        self.policy = policy
        self.worker = worker
        # incarnation nonce is per connection (see Connection.__init__)
        self.nonce = random.getrandbits(63) or 1
        self.peer_nonce = 0
        self.out_seq = 0
        self.in_seq = 0
        self._queue: list[tuple[int, list]] = []    # (seq, iovec) unsent
        self._sent: list[tuple[int, list]] = []     # sent, not yet acked
        self._writer = None      # the OPEN out-_Sock (None while down;
        self._closed = False     # MonClient probes this for liveness)
        self.last_active = time.time()
        self._socks: set[_Sock] = set()
        self._out_running = False
        self._backoff = float(msgr.conf.ms_initial_backoff)
        self._cur = None         # (sock, skey) of the open out session
        self._pump_active = False
        self._pump_delayed = False
        self._retry_timer = None
        msgr.perf.inc("open_connections")
        self._counted = True

    # -- sending (thread-safe entry) -----------------------------------

    def send_message(self, msg: Message) -> None:
        # op shards and client threads land here: the message is handed
        # to the owning loop through its wakeup pipe
        if threading.current_thread() is not self.worker:
            self.msgr.perf.inc("event_wakeups")
        self.worker.call(self._queue_msg, msg)

    def _queue_msg(self, msg: Message) -> None:
        if self._closed:
            return
        msg.src = self.msgr.name
        self.out_seq += 1
        frame = msg.encode_iov(self.out_seq)
        self.msgr.perf.inc("msg_send")
        self.msgr.perf.inc("bytes_send", sum(len(b) for b in frame))
        self._queue.append((self.out_seq, frame))
        self._start_out()
        self._pump()

    def _handle_ack(self, seq: int) -> None:
        self._sent = [(s, f) for s, f in self._sent if s > seq]

    def _requeue_sent(self, peer_in_seq: int) -> None:
        if self._sent:
            self._queue[:0] = self._sent
            self._sent = []
        if peer_in_seq:
            self._queue = [(s, f) for s, f in self._queue
                           if s > peer_in_seq]

    def mark_down(self) -> None:
        self.worker.call(self._close)

    def _close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._writer = None
        self._cur = None
        if self._retry_timer is not None:
            self._retry_timer.cancel()
            self._retry_timer = None
        for s in list(self._socks):
            s.close()
        self._socks.clear()
        if self._counted:
            self._counted = False
            self.msgr.perf.dec("open_connections")

    def __repr__(self):
        return (f"AsyncConnection({self.msgr.name}->{self.peer_name}"
                f"@{self.peer_addr})")

    # -- out side: dial, handshake, session, reconnect -----------------

    def _start_out(self) -> None:
        if self._out_running or self._closed or self.peer_addr is None:
            return
        self._out_running = True
        self._backoff = float(self.msgr.conf.ms_initial_backoff)
        self._attempt()

    def _retry(self, delay: float, fn=None) -> None:
        if self._retry_timer is not None:
            self._retry_timer.cancel()
        self._retry_timer = self.worker.call_later(
            delay, fn if fn is not None else self._attempt)

    def _attempt(self) -> None:
        if self._closed:
            self._out_running = False
            return
        msgr = self.msgr
        if faults.get().partitioned(msgr.name, self.peer_name):
            # lossless links poll at the INITIAL backoff (deterministic
            # heal latency); lossy links reset
            if self.policy.lossy:
                msgr._conn_reset(self)
                return
            self._retry(float(msgr.conf.ms_initial_backoff))
            return
        try:
            raw = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            raw.setblocking(False)
            err = raw.connect_ex(self.peer_addr)
        except OSError:
            self._dial_failed(None)
            return
        if err not in (0, errno.EINPROGRESS, errno.EWOULDBLOCK):
            raw.close()
            self._dial_failed(None)
            return
        holder = []
        sock = _Sock(self.worker, raw, connecting=True,
                     on_connect=lambda: self._handshake(holder[0]),
                     on_resume=lambda: msgr.perf.inc(
                         "partial_write_resumes"))
        holder.append(sock)
        sock.on_error = lambda exc: self._dial_failed(sock)
        self._socks.add(sock)

    def _dial_failed(self, sock: _Sock | None) -> None:
        if sock is not None:
            sock.close()
            self._socks.discard(sock)
        if self._closed:
            self._out_running = False
            return
        if self.policy.lossy:
            self.msgr._conn_reset(self)
            return
        self._retry(self._backoff)
        self._backoff = min(self._backoff * 2,
                            float(self.msgr.conf.ms_max_backoff))

    def _handshake(self, sock: _Sock) -> None:
        if self._closed or sock.closed:
            return
        msgr = self.msgr
        tmo = self.worker.call_later(
            float(msgr.conf.ms_connect_timeout),
            lambda: sock._fail(ConnectionResetError(
                "handshake timeout")))

        def _exit(result, exc):
            tmo.cancel()
            if exc is not None:
                if not isinstance(exc, (AuthError, ConnectionError,
                                        OSError)):
                    msgr.log.error("handshake to %s error: %r",
                                   self.peer_name, exc)
                self._dial_failed(sock)
                return
            skey, peer_in_seq = result
            self._session_open(sock, skey, peer_in_seq)
        _drive(sock, _connect_gen(msgr, self), _exit)

    def _session_open(self, sock: _Sock, skey, peer_in_seq: int) -> None:
        if self._closed or sock.closed:
            sock.close()
            self._socks.discard(sock)
            self._out_running = False
            return
        self._backoff = float(self.msgr.conf.ms_initial_backoff)
        self._writer = sock
        self._requeue_sent(peer_in_seq)
        cur = (sock, skey)
        self._cur = cur
        _drive(sock,
               _frames_gen(self.msgr, self, sock, skey, accepted=False),
               lambda result, exc: self._session_dead(cur, exc))
        self._pump()

    def _session_dead(self, cur, exc) -> None:
        sock, _skey = cur
        sock.close()
        self._socks.discard(sock)
        if self._cur is not cur:
            return
        self._cur = None
        self._writer = None
        self._pump_active = False
        self._pump_delayed = False
        msgr = self.msgr
        unexpected = exc is not None and not isinstance(
            exc, (ConnectionError, OSError))
        if unexpected:
            msgr.log.error("conn loop to %s error: %r",
                           self.peer_name, exc)
        if self._closed:
            self._out_running = False
            return

        def _after():
            if self._closed:
                self._out_running = False
                return
            if self.policy.lossy:
                msgr._conn_reset(self)
                return
            msgr.perf.inc("reconnects")
            self._attempt()
        if unexpected:
            delay = self._backoff
            self._backoff = min(self._backoff * 2,
                                float(msgr.conf.ms_max_backoff))
            self._retry(delay, _after)
        else:
            _after()

    # -- the frame pump ------------------------------------------------

    def _pump(self) -> None:
        while True:
            if self._closed or self._pump_active:
                return
            cur = self._cur
            if cur is None:
                return
            sock, skey = cur
            if sock.closed or not self._queue:
                return
            seq, frame = self._queue[0]
            fs = faults.get()
            msgr = self.msgr
            if not self._pump_delayed:
                if fs.partitioned(msgr.name, self.peer_name):
                    sock._fail(ConnectionResetError("partitioned"))
                    return
                if fs.should_kill_socket(
                        msgr.name, self.peer_name,
                        int(msgr.conf.ms_inject_socket_failures)):
                    msgr.log.debug("injecting socket failure to %s",
                                   self.peer_name)
                    sock._fail(ConnectionResetError("injected"))
                    return
                d = fs.send_delay(msgr.name, self.peer_name)
                if d > 0:
                    self._pump_active = True
                    self._pump_delayed = True

                    def _resume(c=cur):
                        if self._cur is not c or self._closed:
                            return
                        self._pump_active = False
                        self._pump()
                    self.worker.call_later(d, _resume)
                    return
            self._pump_delayed = False
            if fs.should_drop(msgr.name, self.peer_name):
                # modeled message loss (see Messenger._drain_queue)
                self._queue.pop(0)
                if not self.policy.lossy:
                    self._sent.append((seq, frame))
                continue
            # sign at write time, store UNSIGNED: a resend re-signs
            # under the new socket's session key; the iovec is gather-
            # written without joining
            iov = frame if skey is None else \
                frame + [cephx.sign_iov(skey, [b"C", *frame])]
            self._pump_active = True

            def _done(s=seq, f=frame, c=cur):
                if self._cur is not c or self._closed:
                    return
                self._pump_active = False
                if self._queue and self._queue[0][0] == s:
                    self._queue.pop(0)
                    if not self.policy.lossy:
                        self._sent.append((s, f))
                self.last_active = time.time()
                self._pump()
            sock.send_iov(iov, on_done=_done)
            return

    # -- in side: adopt an accepted socket -----------------------------

    def _attach_accepted(self, sock: _Sock, skey, nonce: int,
                         peer_addr) -> None:
        """On self.worker: the peer's connect finished its handshake;
        adopt the socket and run the frame loop on it (the tail of
        Messenger._accept)."""
        msgr = self.msgr
        if self._closed:
            sock.close()
            return
        self._socks.add(sock)
        if self.peer_nonce != nonce:
            # new peer incarnation: fresh seq space, maybe new address
            self.peer_nonce = nonce
            self.in_seq = 0
            self.peer_addr = peer_addr
        sock.send_iov([_BANNER_REPLY.pack(BANNER_MAGIC, self.in_seq)])
        msgr.perf.inc("accepts")

        def _exit(result, exc):
            if exc is not None and not isinstance(
                    exc, (ConnectionError, OSError)):
                msgr.log.error("accept loop for %s died: %r",
                               self.peer_name, exc)
            sock.close()
            self._socks.discard(sock)
        _drive(sock,
               _frames_gen(msgr, self, sock, skey, accepted=True),
               _exit)
