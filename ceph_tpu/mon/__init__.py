"""Monitor tier: Paxos-replicated cluster state (mon/ analog).

A small odd quorum of monitors agrees (single Paxos value sequence, mon/
Paxos.cc protocol) on every piece of cluster state: the monmap, the
OSDMap + EC profiles, auth, health.  Daemons and clients keep a
MonClient session for maps, subscriptions and admin commands.
"""

from .monmap import MonMap
from .monitor import Monitor
from .client import MonClient

__all__ = ["MonMap", "Monitor", "MonClient"]
