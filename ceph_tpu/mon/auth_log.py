"""AuthMonitor + LogMonitor: paxos-replicated keyring and cluster log
(mon/AuthMonitor.cc + mon/LogMonitor.cc reduced).

AuthMonitor owns the cluster keyring: `auth add/get-or-create/get/rm/
ls/export` commands mutate it through paxos, so every mon serves the
same keys and a restart loses nothing.  `auth export` emits the
keyring-file format the session layer consumes (auth/keyring.py) —
the `ceph auth get-or-create > keyring` provisioning flow.

LogMonitor is the cluster log: daemons send MLogMsg entries (and the
OSDMonitor logs its own state transitions); batches commit through
paxos and `log last [n]` reads them back, with old versions trimmed.
"""

from __future__ import annotations

from ..utils import denc
from .services import PaxosService


class AuthMonitor(PaxosService):
    name = "authm"

    def __init__(self, mon):
        super().__init__(mon)
        # entity -> {"key": b64 str, "caps": str}
        self.keys: dict[str, dict] = {}
        self.pending_keys: dict[str, dict] | None = None
        self._last_proposed = 0
        self.update_from_paxos()

    # -- paxos plumbing ----------------------------------------------------

    def update_from_paxos(self) -> None:
        v = self.version
        if v <= 0 or v == getattr(self, "_applied_v", 0):
            # a FOREIGN service's commit must not clear our queued
            # pending state (OSDMonitor guards on epoch the same way)
            return
        self._applied_v = v
        blob = self.mon.store.get_version(self.name, v)
        if blob is not None:
            self.keys = denc.loads(blob)
        self.have_pending = False
        self.pending_keys = None

    def create_pending(self) -> None:
        self.pending_keys = {k: dict(m) for k, m in self.keys.items()}
        self.have_pending = True

    def _pending(self) -> dict:
        if not self.have_pending or self.pending_keys is None:
            self.create_pending()
        return self.pending_keys

    def encode_pending(self, txn_ops: list) -> None:
        v = max(self.version, self._last_proposed) + 1
        txn_ops.append(("set", self.name, f"{v:020d}",
                        denc.dumps(self.pending_keys)))
        txn_ops.append(("set", self.name, "last_committed",
                        str(v).encode()))
        # each version is a full (small) snapshot: older ones are dead
        if v > 2:
            txn_ops.append(("rm", self.name, f"{v - 2:020d}", b""))
        self._last_proposed = v

    # -- commands ----------------------------------------------------------

    def dispatch_command(self, cmd: dict):
        prefix = cmd.get("prefix", "")
        if not prefix.startswith("auth "):
            return None
        from ..auth.keyring import generate_key
        entity = cmd.get("entity", "")
        if prefix == "auth ls":
            lines = [f"{e} caps={m.get('caps', '')!r}"
                     for e, m in sorted(self.keys.items())]
            return 0, "\n".join(lines), b""
        if prefix == "auth get":
            m = self.keys.get(entity)
            if m is None:
                return -2, f"no such entity {entity!r}", b""
            return 0, self._export_one(entity, m), b""
        if prefix == "auth export":
            text = "".join(self._export_one(e, m) + "\n"
                           for e, m in sorted(self.keys.items()))
            return 0, text, text.encode()
        if prefix in ("auth add", "auth get-or-create"):
            if not entity:
                return -22, "entity required", b""
            if entity in self.keys:
                if prefix == "auth add":
                    return -17, f"{entity} already has a key", b""
                return 0, self._export_one(entity,
                                           self.keys[entity]), b""
            pend = self._pending()
            if entity in pend:
                # a second get-or-create racing the uncommitted
                # proposal must see the SAME key — regenerating would
                # invalidate the first caller's copy on commit
                if prefix == "auth add":
                    return -17, f"{entity} already has a key", b""
                return 0, self._export_one(entity, pend[entity]), b""
            pend[entity] = {"key": cmd.get("key") or generate_key(),
                            "caps": cmd.get("caps", "")}
            self.propose_pending()
            return 0, self._export_one(entity, pend[entity]), b""
        if prefix == "auth rm":
            if entity not in self.keys:
                return -2, f"no such entity {entity!r}", b""
            pend = self._pending()
            pend.pop(entity, None)
            self.propose_pending()
            return 0, f"removed {entity}", b""
        return -22, f"unknown auth command {prefix!r}", b""

    @staticmethod
    def _export_one(entity: str, m: dict) -> str:
        return f"[{entity}]\nkey = {m['key']}\n"


class LogMonitor(PaxosService):
    name = "logm"
    MAX_KEEP = 500                   # in-memory + store retention

    def __init__(self, mon):
        super().__init__(mon)
        self.entries: list[dict] = []
        self.pending_entries: list[dict] = []
        self._applied = 0
        self._last_proposed = 0
        self.update_from_paxos()

    # -- paxos plumbing ----------------------------------------------------

    def update_from_paxos(self) -> None:
        v = self.version
        if self._applied >= v:
            return                   # foreign commit: keep pending
        while self._applied < v:
            self._applied += 1
            blob = self.mon.store.get_version(self.name, self._applied)
            if blob is None:
                continue             # trimmed
            self.entries.extend(denc.loads(blob))
        if len(self.entries) > self.MAX_KEEP:
            del self.entries[: len(self.entries) - self.MAX_KEEP]
        self.have_pending = False

    def create_pending(self) -> None:
        self.have_pending = True

    def encode_pending(self, txn_ops: list) -> None:
        v = max(self.version, self._last_proposed) + 1
        txn_ops.append(("set", self.name, f"{v:020d}",
                        denc.dumps(self.pending_entries)))
        txn_ops.append(("set", self.name, "last_committed",
                        str(v).encode()))
        if v > self.MAX_KEEP:
            txn_ops.append(("rm", self.name,
                            f"{v - self.MAX_KEEP:020d}", b""))
        self.pending_entries = []
        self._last_proposed = v

    # -- entry points ------------------------------------------------------

    def log_entry(self, src: str, level: str,
                  text: str) -> None:
        """Queue one cluster-log entry (leader only; peons forward
        their daemons' MLogMsg traffic to the leader)."""
        self.pending_entries.append({
            "stamp": self.mon.clock.now(), "src": src,
            "level": level, "text": text})
        if not self.have_pending:
            self.create_pending()
        self.propose_pending()

    def handle_log(self, msg) -> None:
        for ent in msg.entries:
            self.pending_entries.append({
                "stamp": ent.get("stamp", self.mon.clock.now()),
                "src": msg.src, "level": ent.get("level", "INF"),
                "text": ent.get("text", "")})
        if self.pending_entries:
            if not self.have_pending:
                self.create_pending()
            self.propose_pending()

    def dispatch_command(self, cmd: dict):
        prefix = cmd.get("prefix", "")
        if prefix == "log last":
            try:
                n = int(cmd.get("num", 20))
            except (TypeError, ValueError):
                return -22, "bad num", b""
            lines = [f"{e['stamp']:.3f} {e['src']} [{e['level']}] "
                     f"{e['text']}" for e in self.entries[-n:]]
            return 0, "\n".join(lines), b""
        if prefix == "log":
            text = cmd.get("text", "")
            if not text:
                return -22, "text required", b""
            self.log_entry(cmd.get("src", "client"), "INF", text)
            return 0, "logged", b""
        return None
