"""AuthMonitor + LogMonitor: paxos-replicated keyring and cluster log
(mon/AuthMonitor.cc + mon/LogMonitor.cc reduced).

AuthMonitor owns the cluster keyring: `auth add/get-or-create/get/rm/
ls/export` commands mutate it through paxos, so every mon serves the
same keys and a restart loses nothing.  `auth export` emits the
keyring-file format the session layer consumes (auth/keyring.py) —
the `ceph auth get-or-create > keyring` provisioning flow.

LogMonitor is the cluster log: daemons send MLogMsg entries (and the
OSDMonitor logs its own state transitions); batches commit through
paxos and `log last [n]` reads them back, with old versions trimmed.
"""

from __future__ import annotations

from ..utils import denc
from .services import PaxosService


class AuthMonitor(PaxosService):
    name = "authm"

    def __init__(self, mon):
        super().__init__(mon)
        # entity -> {"key": b64 str, "caps": str}
        self.keys: dict[str, dict] = {}
        self.pending_keys: dict[str, dict] | None = None
        self._last_proposed = 0
        self.update_from_paxos()

    # -- paxos plumbing ----------------------------------------------------

    def update_from_paxos(self) -> None:
        v = self.version
        if v <= 0 or v == getattr(self, "_applied_v", 0):
            # a FOREIGN service's commit must not clear our queued
            # pending state (OSDMonitor guards on epoch the same way)
            return
        self._applied_v = v
        blob = self.mon.store.get_version(self.name, v)
        if blob is not None:
            self.keys = denc.loads(blob)
        self.have_pending = False
        self.pending_keys = None

    def create_pending(self) -> None:
        self.pending_keys = {k: dict(m) for k, m in self.keys.items()}
        self.have_pending = True

    def _pending(self) -> dict:
        if not self.have_pending or self.pending_keys is None:
            self.create_pending()
        return self.pending_keys

    def encode_pending(self, txn_ops: list) -> None:
        v = max(self.version, self._last_proposed) + 1
        txn_ops.append(("set", self.name, f"{v:020d}",
                        denc.dumps(self.pending_keys)))
        txn_ops.append(("set", self.name, "last_committed",
                        str(v).encode()))
        # each version is a full (small) snapshot: older ones are dead
        if v > 2:
            txn_ops.append(("rm", self.name, f"{v - 2:020d}", b""))
        self._last_proposed = v

    # -- commands ----------------------------------------------------------

    # -- rotating service secrets + ticket granting ------------------------
    #
    # CephxProtocol.h:143 (CephXTicketBlob) + KeyServer rotating
    # secrets, reduced: per service class the monitor keeps the
    # CURRENT and PREVIOUS rotating secret (so tickets sealed just
    # before a rotation stay redeemable until they expire); `auth
    # get-ticket` seals a fresh connection secret + expiry under the
    # current one; service daemons fetch the rotating pair over their
    # authenticated mon channel and never see client keyring entries.

    _ROT_KEY = "\x00rotating"        # reserved key in the auth blob

    def _rotating(self, pend=None) -> dict:
        src = pend if pend is not None else self.keys
        return src.get(self._ROT_KEY, {})

    def _rotate(self, service: str):
        """Stage a new rotating secret for `service` (keeps one
        previous); returns the new key id."""
        from ..auth import cephx
        import base64
        pend = self._pending()
        rot = dict(self._rotating(pend))
        cur = list(rot.get(service, []))
        new_id = (cur[0]["id"] + 1) if cur else 1
        cur.insert(0, {
            "id": new_id,
            "secret": base64.b64encode(cephx.make_secret()).decode(),
            "created": self.mon.clock.now()})
        rot[service] = cur[:2]
        pend[self._ROT_KEY] = rot
        self.propose_pending()
        return new_id

    def _cmd_get_ticket(self, cmd: dict):
        from ..auth import cephx
        from ..utils import denc as _denc
        import base64
        import os
        service = cmd.get("service", "")
        if not service or not service.isalnum():
            return -22, f"bad service {service!r}", b""
        rot = self._rotating().get(service)
        if not rot:
            # lazy first use: create the service's rotating secret
            # (a write -> rides paxos; the deferred-ack machinery
            # answers the client only after commit)
            self._rotate(service)
            rot = self._rotating(self.pending_keys).get(service)
        secret = base64.b64decode(rot[0]["secret"])
        ttl = float(self.mon.conf.auth_service_ticket_ttl)
        conn_key = os.urandom(32)
        expires = self.mon.clock.now() + ttl
        blob = cephx.seal(secret, _denc.dumps({
            "client": cmd.get("_requester", "client.?"),
            "key": conn_key, "expires": expires,
            "service": service}))
        out = _denc.dumps({"blob": blob, "key": conn_key,
                           "expires": expires, "service": service,
                           "key_id": rot[0]["id"]})
        return 0, f"ticket for {service}", out

    def _cmd_get_rotating(self, cmd: dict):
        from ..utils import denc as _denc
        service = cmd.get("service", "")
        requester = str(cmd.get("_requester", ""))
        # only a daemon of the class (or a mon) may fetch the
        # service's rotating secrets
        if not (requester.startswith(f"{service}.")
                or requester.startswith("mon.")):
            return -13, (f"{requester} may not read {service} "
                         f"rotating keys"), b""      # EACCES
        rot = self._rotating().get(service)
        if not rot:
            self._rotate(service)
            rot = self._rotating(self.pending_keys).get(service)
        return 0, f"{len(rot)} rotating keys", _denc.dumps(rot)

    def dispatch_command(self, cmd: dict):
        prefix = cmd.get("prefix", "")
        if not prefix.startswith("auth "):
            return None
        from ..auth.keyring import generate_key
        entity = cmd.get("entity", "")
        if entity.startswith("\x00"):
            return -22, "bad entity name", b""
        if prefix == "auth rotate":
            service = cmd.get("service", "")
            if not service or not service.isalnum():
                return -22, f"bad service {service!r}", b""
            new_id = self._rotate(service)
            return 0, f"rotated {service} key (id {new_id})", b""
        if prefix == "auth get-ticket":
            return self._cmd_get_ticket(cmd)
        if prefix == "auth get-rotating":
            return self._cmd_get_rotating(cmd)
        if prefix == "auth ls":
            lines = [f"{e} caps={m.get('caps', '')!r}"
                     for e, m in sorted(self.keys.items())
                     if not e.startswith("\x00")]
            return 0, "\n".join(lines), b""
        if prefix == "auth get":
            m = self.keys.get(entity)
            if m is None:
                return -2, f"no such entity {entity!r}", b""
            return 0, self._export_one(entity, m), b""
        if prefix == "auth export":
            text = "".join(self._export_one(e, m) + "\n"
                           for e, m in sorted(self.keys.items())
                           if not e.startswith("\x00"))
            return 0, text, text.encode()
        if prefix in ("auth add", "auth get-or-create"):
            if not entity:
                return -22, "entity required", b""
            if entity in self.keys:
                if prefix == "auth add":
                    return -17, f"{entity} already has a key", b""
                return 0, self._export_one(entity,
                                           self.keys[entity]), b""
            pend = self._pending()
            if entity in pend:
                # a second get-or-create racing the uncommitted
                # proposal must see the SAME key — regenerating would
                # invalidate the first caller's copy on commit
                if prefix == "auth add":
                    return -17, f"{entity} already has a key", b""
                return 0, self._export_one(entity, pend[entity]), b""
            pend[entity] = {"key": cmd.get("key") or generate_key(),
                            "caps": cmd.get("caps", "")}
            self.propose_pending()
            return 0, self._export_one(entity, pend[entity]), b""
        if prefix == "auth rm":
            if entity not in self.keys:
                return -2, f"no such entity {entity!r}", b""
            pend = self._pending()
            pend.pop(entity, None)
            self.propose_pending()
            return 0, f"removed {entity}", b""
        return -22, f"unknown auth command {prefix!r}", b""

    @staticmethod
    def _export_one(entity: str, m: dict) -> str:
        return f"[{entity}]\nkey = {m['key']}\n"


class LogMonitor(PaxosService):
    name = "logm"
    MAX_KEEP = 500                   # in-memory + store retention

    def __init__(self, mon):
        super().__init__(mon)
        self.entries: list[dict] = []
        self.pending_entries: list[dict] = []
        self._applied = 0
        self._last_proposed = 0
        self.update_from_paxos()

    # -- paxos plumbing ----------------------------------------------------

    def update_from_paxos(self) -> None:
        v = self.version
        if self._applied >= v:
            return                   # foreign commit: keep pending
        while self._applied < v:
            self._applied += 1
            blob = self.mon.store.get_version(self.name, self._applied)
            if blob is None:
                continue             # trimmed
            self.entries.extend(denc.loads(blob))
        if len(self.entries) > self.MAX_KEEP:
            del self.entries[: len(self.entries) - self.MAX_KEEP]
        self.have_pending = False

    def create_pending(self) -> None:
        self.have_pending = True

    def encode_pending(self, txn_ops: list) -> None:
        v = max(self.version, self._last_proposed) + 1
        txn_ops.append(("set", self.name, f"{v:020d}",
                        denc.dumps(self.pending_entries)))
        txn_ops.append(("set", self.name, "last_committed",
                        str(v).encode()))
        if v > self.MAX_KEEP:
            txn_ops.append(("rm", self.name,
                            f"{v - self.MAX_KEEP:020d}", b""))
        self.pending_entries = []
        self._last_proposed = v

    # -- entry points ------------------------------------------------------

    def log_entry(self, src: str, level: str,
                  text: str) -> None:
        """Queue one cluster-log entry (leader only; peons forward
        their daemons' MLogMsg traffic to the leader)."""
        self.pending_entries.append({
            "stamp": self.mon.clock.now(), "src": src,
            "level": level, "text": text})
        if not self.have_pending:
            self.create_pending()
        self.propose_pending()

    def handle_log(self, msg) -> None:
        for ent in msg.entries:
            self.pending_entries.append({
                "stamp": ent.get("stamp", self.mon.clock.now()),
                "src": msg.src, "level": ent.get("level", "INF"),
                "text": ent.get("text", "")})
        if self.pending_entries:
            if not self.have_pending:
                self.create_pending()
            self.propose_pending()

    def dispatch_command(self, cmd: dict):
        prefix = cmd.get("prefix", "")
        if prefix == "log last":
            try:
                n = int(cmd.get("num", 20))
            except (TypeError, ValueError):
                return -22, "bad num", b""
            lines = [f"{e['stamp']:.3f} {e['src']} [{e['level']}] "
                     f"{e['text']}" for e in self.entries[-n:]]
            return 0, "\n".join(lines), b""
        if prefix == "log":
            text = cmd.get("text", "")
            if not text:
                return -22, "text required", b""
            self.log_entry(cmd.get("src", "client"), "INF", text)
            return 0, "logged", b""
        return None
