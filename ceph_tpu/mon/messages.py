"""Monitor wire messages (messages/MMon*.h analogs)."""

from __future__ import annotations

from ..msg import Message, register_message


@register_message
class MMonElection(Message):
    """op: propose | ack | victory (mon/Elector protocol)."""
    TYPE = 100
    # fields: op, epoch, rank, quorum (victory)


@register_message
class MMonPaxos(Message):
    """op: collect|last|begin|accept|commit|lease|lease_ack."""
    TYPE = 101
    # fields: op, pn, last_committed, first_committed, version,
    #         value (txn blob), lease_expire, commits {v: blob}


@register_message
class MMonCommand(Message):
    TYPE = 102
    # fields: tid, cmd (dict with "prefix" + args)


@register_message
class MMonCommandAck(Message):
    TYPE = 103
    # fields: tid, retval, out (str), data (bytes)


@register_message
class MMonSubscribe(Message):
    TYPE = 104
    # fields: what: {"osdmap": start_epoch, "monmap": ...}


@register_message
class MMonMap(Message):
    TYPE = 105
    # fields: monmap (bytes)


@register_message
class MOSDMapMsg(Message):
    """Full map or incrementals published to subscribers."""
    TYPE = 106
    # fields: full (bytes | None), incrementals (list[bytes]), epoch


@register_message
class MOSDBoot(Message):
    TYPE = 107
    # fields: osd_id, addr, heartbeat_addr


@register_message
class MOSDFailure(Message):
    TYPE = 108
    # fields: target_osd, reporter, failed_for (seconds)


@register_message
class MOSDAlive(Message):
    TYPE = 109
    # fields: osd_id, epoch


@register_message
class MPGTemp(Message):
    """Primary requests pg_temp overrides (MOSDPGTemp analog)."""
    TYPE = 110
    # fields: osd_id, pg_temp: {pgid_str: [osds]}


@register_message
class MMonGetVersion(Message):
    TYPE = 111
    # fields: tid, what


@register_message
class MMonGetVersionReply(Message):
    TYPE = 112
    # fields: tid, version


@register_message
class MMgrBeacon(Message):
    """mgr -> mon: i am (still) the active mgr (messages/MMgrBeacon.h)."""
    TYPE = 113
    # fields: name, addr


@register_message
class MMgrReport(Message):
    """daemon -> mgr: perf counter report (messages/MMgrReport.h)."""
    TYPE = 114
    # fields: entity, counters (perf dump dict), epoch


@register_message
class MMDSBeacon(Message):
    """mds -> mon: active mds registration (messages/MMDSBeacon.h).

    `rank` places the daemon in the multi-rank FSMap (metadata
    namespace sharded across ranks, SURVEY §2.3)."""
    TYPE = 115
    # fields: name, addr, rank (default 0)


@register_message
class MPGStats(Message):
    """osd -> mon: per-pg stats from primaries (messages/MPGStats.h);
    the PGMonitor/PGMap feed that health summaries aggregate."""
    TYPE = 116
    # fields: osd_id, epoch, stats {pgid_str: {"state", "objects",
    #         "live", "acting"}}


@register_message
class MLogMsg(Message):
    """daemon/client -> mon: cluster log entries (messages/MLog.h);
    the LogClient feed behind `ceph log last`."""
    TYPE = 117
    # fields: entries [{stamp, level, text}]
