"""Leader election (mon/Elector.h:34 analog).

Lowest-rank live monitor wins.  A candidate proposes itself with a
fresh epoch; peers ack anyone with a lower rank than any candidate they
have acked this epoch (deferring), or counter-propose if they outrank
the candidate.  Majority of acks -> victory broadcast with the quorum.
Epochs are bumped on every election so stale messages are discarded.
"""

from __future__ import annotations

from typing import Callable

from ..utils.dout import DoutLogger
from .messages import MMonElection
from .monmap import MonMap

PROPOSE = "propose"
ACK = "ack"
VICTORY = "victory"


class Elector:
    def __init__(self, name: str, monmap: MonMap,
                 send: Callable[[str, MMonElection], None],
                 on_win: Callable[[int, list[str]], None],
                 on_lose: Callable[[int, str, list[str]], None],
                 schedule: Callable[[float, Callable], object] | None = None,
                 cancel: Callable[[object], None] | None = None,
                 timeout: float = 1.0):
        import threading

        def _sched(delay, fn):
            t = threading.Timer(delay, fn)
            t.daemon = True
            t.start()
            return t

        self.name = name
        self.monmap = monmap
        self.send = send                  # send(peer_name, msg)
        self.on_win = on_win              # on_win(epoch, quorum)
        self.on_lose = on_lose            # on_lose(epoch, leader, quorum)
        self.schedule = schedule or _sched
        self.cancel = cancel or (lambda t: t.cancel())
        self.timeout = timeout
        self.log = DoutLogger("elector", name)
        self.epoch = 1
        self.electing = False
        self.acked: str | None = None     # whom we acked this epoch
        self.acks: set[str] = set()
        self.leader: str | None = None
        self.quorum: list[str] = []
        self._victory_timer = None
        self._restart_timer = None
        # a mon removed from the map steps out: its name has no rank
        # in the new roster, so any election activity would throw
        self.disabled = False

    @property
    def rank(self) -> int:
        return self.monmap.rank_of(self.name)

    def start(self) -> None:
        """Begin (or restart) an election round."""
        if self.disabled:
            return
        self._cancel_victory()
        self.epoch += 1
        self.electing = True
        self.acked = self.name
        self.acks = {self.name}
        self.leader = None
        self.log.debug("start election epoch %d", self.epoch)
        for peer in self.monmap.ranks():
            if peer != self.name:
                self.send(peer, MMonElection(op=PROPOSE, epoch=self.epoch,
                                             rank=self.rank, quorum=[]))
        self._arm_restart()
        self._check_victory()

    def _arm_restart(self) -> None:
        """Liveness: an election that neither wins nor loses within the
        full timeout restarts with a fresh epoch (the reference's
        expire_election) — e.g. our propose raced a round that excluded
        us, so peers drop our now-stale epoch on the floor."""
        self._cancel_restart()
        epoch_at = self.epoch
        self._restart_timer = self.schedule(
            self.timeout * 5, lambda: self._restart_timeout(epoch_at))

    def _restart_timeout(self, epoch: int) -> None:
        self._restart_timer = None
        if self.electing and epoch == self.epoch:
            self.log.debug("election epoch %d expired, restarting", epoch)
            self.start()

    def stop(self) -> None:
        """Step out permanently (removed from the roster): cancel any
        armed victory/restart timers — a mid-candidacy removed mon
        must not fire _declare_victory from a stale timer — and go
        inert."""
        self.disabled = True
        self.electing = False
        self._cancel_victory()
        self._cancel_restart()
        self.leader = None
        self.quorum = []

    def _cancel_restart(self) -> None:
        if self._restart_timer is not None:
            try:
                self.cancel(self._restart_timer)
            except Exception:
                pass
            self._restart_timer = None

    def handle(self, msg: MMonElection) -> None:
        if self.disabled:
            return                        # removed from the roster
        if msg.epoch < self.epoch and msg.op != VICTORY:
            return                        # stale round
        if msg.op == PROPOSE:
            self._handle_propose(msg)
        elif msg.op == ACK:
            self._handle_ack(msg)
        elif msg.op == VICTORY:
            self._handle_victory(msg)

    def _handle_propose(self, msg: MMonElection) -> None:
        peer = msg.src
        peer_rank = msg.rank
        if msg.epoch > self.epoch:
            self.epoch = msg.epoch
            self.electing = True
            self.acked = None
            self.acks = set()
            self._cancel_victory()
            self._arm_restart()
        if peer_rank < self.rank:
            # candidate outranks us: defer unless we already acked better
            if (self.acked is None
                    or self.monmap.rank_of(self.acked) > peer_rank):
                self.acked = peer
                self._cancel_victory()     # our candidacy is over
                self.send(peer, MMonElection(op=ACK, epoch=self.epoch,
                                             rank=self.rank, quorum=[]))
        else:
            # we outrank the candidate: push our own candidacy
            if self.acked != self.name:
                self.epoch += 1
                self.electing = True
                self.acked = self.name
                self.acks = {self.name}
                self._arm_restart()
                for p in self.monmap.ranks():
                    if p != self.name:
                        self.send(p, MMonElection(
                            op=PROPOSE, epoch=self.epoch, rank=self.rank,
                            quorum=[]))

    def _handle_ack(self, msg: MMonElection) -> None:
        if not self.electing or self.acked != self.name:
            return
        if msg.epoch != self.epoch:
            return
        self.acks.add(msg.src)
        self._check_victory()

    def _cancel_victory(self) -> None:
        if self._victory_timer is not None:
            try:
                self.cancel(self._victory_timer)
            except Exception:
                pass
            self._victory_timer = None

    def _check_victory(self) -> None:
        """Declare immediately with ALL acks; with a bare majority wait
        out the election timeout so a better-ranked candidate's propose
        can still preempt us (the reference's expire_election model)."""
        if self.acked != self.name or not self.electing:
            return
        if len(self.acks) >= self.monmap.size:
            self._declare_victory()
        elif (len(self.acks) >= self.monmap.quorum_needed()
                and self._victory_timer is None):
            epoch_at_schedule = self.epoch
            self._victory_timer = self.schedule(
                self.timeout,
                lambda: self._victory_timeout(epoch_at_schedule))

    def _victory_timeout(self, epoch: int) -> None:
        self._victory_timer = None
        if (self.electing and self.acked == self.name
                and epoch == self.epoch
                and len(self.acks) >= self.monmap.quorum_needed()):
            self._declare_victory()

    def _declare_victory(self) -> None:
        self._cancel_victory()
        self._cancel_restart()
        quorum = sorted(self.acks, key=self.monmap.rank_of)
        self.epoch += 1
        self.electing = False
        self.leader = self.name
        self.quorum = quorum
        self.log.info("won election epoch %d quorum %s",
                      self.epoch, quorum)
        for peer in quorum:
            if peer != self.name:
                self.send(peer, MMonElection(
                    op=VICTORY, epoch=self.epoch, rank=self.rank,
                    quorum=quorum))
        self.on_win(self.epoch, quorum)

    def _handle_victory(self, msg: MMonElection) -> None:
        if msg.epoch < self.epoch:
            return
        self._cancel_victory()
        self._cancel_restart()
        self.epoch = msg.epoch
        self.electing = False
        self.leader = msg.src
        self.quorum = list(msg.quorum)
        self.log.info("lost election to %s epoch %d", msg.src, self.epoch)
        self.on_lose(self.epoch, msg.src, self.quorum)
