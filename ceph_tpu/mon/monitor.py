"""The monitor daemon (mon/Monitor.cc analog).

Owns the messenger, elector, paxos and services under one big lock
(the reference's Monitor::lock model).  Handles:
  * elections + paxos traffic between quorum peers;
  * client/daemon sessions: subscriptions (osdmap pushed on commit),
    admin commands (forwarded to the leader, answered after commit);
  * OSD lifecycle: boot, failure reports, pg_temp, down->out ticks.
"""

from __future__ import annotations

from ..utils import denc
import threading
import uuid
from typing import Callable

from ..msg import Dispatcher, Message, Policy, create_messenger
from ..utils.clock import SystemClock
from ..utils.config import Config
from ..utils.dout import DoutLogger
from .elector import Elector
from .messages import (MLogMsg, MMDSBeacon, MMgrBeacon, MMonCommand,
                       MMonCommandAck, MMonElection, MMonMap, MMonPaxos,
                       MMonSubscribe, MOSDBoot, MOSDFailure, MOSDMapMsg,
                       MPGStats, MPGTemp)
from .monmap import MonMap
from .paxos import Paxos
from .services import MonmapMonitor, OSDMonitor, PaxosService
from .store import MonitorDBStore


class Monitor(Dispatcher):
    def __init__(self, name: str, monmap: MonMap, conf: Config | None = None,
                 store_path: str = "", clock=None,
                 store: MonitorDBStore | None = None):
        self.name = name                       # short name, e.g. "a"
        self.entity = f"mon.{name}"
        # private copy: membership changes arrive through paxos
        # (adopt_monmap), never by another daemon mutating a shared map
        self.monmap = monmap.copy()
        self.conf = conf or Config()
        self.clock = clock or SystemClock()
        self.log = DoutLogger("mon", self.entity)
        self.lock = threading.RLock()

        # `store` lets a crash-restart cycle remount the SAME store a
        # killed mon left behind (vstart restart_mon)
        self.store = store if store is not None else \
            MonitorDBStore(store_path)
        self.store.open()
        self.store.owner = self.entity
        self.store.crash_callback = self._on_store_crash
        # torn-commit detection BEFORE paxos/services read the store:
        # a half-applied commit transaction must never be adopted —
        # the claim rolls back to the sealed floor and the quorum
        # re-shares the lost tail (Protocol-Aware Recovery)
        self.store.check_integrity()

        self.msgr = create_messenger(self.entity, conf=self.conf)
        self.msgr.bind(monmap.addr_of(name))
        self.msgr.set_policy("mon", Policy.lossless_peer())
        self.msgr.set_policy("osd", Policy.stateless_server())
        self.msgr.set_policy("client", Policy.stateless_server())
        self.msgr.add_dispatcher_tail(self)

        def _sched(delay, fn):
            def locked_fn():
                if self._stopped:
                    return    # timers may outlive the messenger
                with self.lock:
                    fn()
            return self.clock.timer(delay, locked_fn)

        self.elector = Elector(self.entity_name, self._mon_monmap(),
                               self._send_mon, self._won, self._lost,
                               schedule=_sched,
                               timeout=float(self.conf.mon_election_timeout)
                               / 5.0)
        self.paxos = Paxos(self.entity, self.store, self._send_mon,
                           self._on_commit,
                           lease_duration=float(self.conf.mon_lease),
                           clock=self.clock, schedule=_sched,
                           on_stall=self.elector.start,
                           phase_timeout=float(
                               self.conf.mon_lease_ack_timeout),
                           trim_max=int(self.conf.paxos_max_versions),
                           trim_keep=int(self.conf.paxos_trim_keep))
        self.paxos.on_active = self._on_paxos_active
        # sessions first: MonmapMonitor's constructor may adopt a
        # persisted monmap, which re-publishes to subscribers (and may
        # discover we were removed while down)
        self.subs: dict[str, dict] = {}
        self._pending_acks: list[tuple] = []
        self._proposing: list[PaxosService] = []
        self._removed = False

        self.services: dict[str, PaxosService] = {}
        self.osdmon = OSDMonitor(self)
        self.monmon = MonmapMonitor(self)
        from .auth_log import AuthMonitor, LogMonitor
        self.authmon = AuthMonitor(self)
        self.logmon = LogMonitor(self)
        self.services["osdmap"] = self.osdmon
        self.services["monmap"] = self.monmon
        self.services["authm"] = self.authmon
        self.services["logm"] = self.logmon

        self._tick_timer = None
        self._stopped = False
        self._boot_time = self.clock.now()
        self._ticks = 0

        # observability
        from ..utils.admin_socket import AdminSocket
        from ..utils.perf_counters import (PerfCountersBuilder,
                                           PerfCountersCollection)
        self.perf_collection = PerfCountersCollection()
        self.perf = (PerfCountersBuilder("mon")
                     .add_u64_counter("elections_won")
                     .add_u64_counter("elections_lost")
                     .add_u64_counter("commands")
                     .create_perf_counters())
        self.paxos.perf = (PerfCountersBuilder("paxos")
                           .add_u64_counter("collect")
                           .add_u64_counter("begin")
                           .add_u64_counter("commit")
                           .add_u64_counter("lease")
                           .create_perf_counters())
        self.perf_collection.add(self.perf)
        self.perf_collection.add(self.paxos.perf)
        self.perf_collection.add(self.msgr.perf)
        # op tracing: leader-handled commands become tracked ops whose
        # paxos.propose / paxos.commit spans (fed by self.paxos.tracer)
        # expose where a write spent its consensus time — same dump
        # surface as the OSD plane, so tools/trace_dump.py merges mon
        # consensus lanes into the one Chrome trace
        from ..utils.optracker import OpTracker
        self.op_tracker = OpTracker(
            self.clock,
            complaint_age=float(self.conf.osd_op_complaint_time),
            logger=self.log, daemon=self.entity)
        self._cmd_ops: list = []       # [trk, phase] holders in flight
        self.paxos.tracer = self._paxos_trace
        sock_dir = str(self.conf.admin_socket_dir)
        self.asok = AdminSocket(
            self.entity,
            path=f"{sock_dir}/{self.entity}.asok" if sock_dir else "")
        self.asok.register("perf dump", lambda c: self._perf_dump())
        self.asok.register("dump_ops_in_flight",
                           lambda c: self.op_tracker.dump_ops_in_flight())
        self.asok.register("dump_historic_ops",
                           lambda c: self.op_tracker.dump_historic_ops())
        self.asok.register(
            "dump_historic_slow_ops",
            lambda c: self.op_tracker.dump_historic_slow_ops())
        self.asok.register("config show", lambda c: self.conf.dump())
        self.asok.register("quorum_status", lambda c: {
            "leader": self.elector.leader,
            "quorum": list(self.elector.quorum),
            "election_epoch": self.elector.epoch})
        self.asok.register("status", lambda c: self._cmd_status()[1])
        # fault-injection surface (FaultSet install/clear/dump)
        from ..utils import faults
        faults.get().register_asok(self.asok)
        # flight recorder: mons contribute identity + quorum + crash
        # state + their tracked command ops (with paxos spans) to
        # every incident capture
        from ..utils import optracker
        optracker.recorder().register(self.entity, self._flight_dump)
        frd = str(getattr(self.conf, "flight_recorder_dir", "") or "")
        if frd:
            optracker.recorder().arm(
                frd, int(self.conf.flight_recorder_max))

    MON_CRASH_SITES = ["paxos.pre_commit", "paxos.mid_commit",
                       "paxos.post_accept_pre_ack"]

    def _flight_dump(self) -> dict:
        """Flight-recorder contribution: identity/quorum + crash
        state, plus the tracked command ops whose paxos.propose /
        paxos.commit spans date a consensus wedge."""
        d = self._perf_dump()
        return {"daemon": d["daemon"], "crash": d["crash"],
                "ops_in_flight": self.op_tracker.dump_ops_in_flight(),
                "historic_ops": self.op_tracker.dump_historic_ops()}

    def _perf_dump(self) -> dict:
        from ..utils import faults
        out = self.perf_collection.dump()
        # daemon info block (every reference daemon answers `status`
        # with identity/uptime facts; OSDs report the same schema)
        out["daemon"] = {
            "entity": self.entity,
            "role": "mon",
            "uptime": round(self.clock.now() - self._boot_time, 3),
            "ticks": self._ticks,
            "store_backend": type(self.store).__name__,
            "conf_epoch": self.conf.generation,
            "osdmap_epoch": self.osdmon.osdmap.epoch,
            "quorum": list(self.elector.quorum),
        }
        out["crash"] = {
            "crashed": int(bool(self.store.frozen)),
            "site": self.store.crash_site,
            "crash_rules": sum(1 for r in faults.get().rules()
                               if r.kind == "crash"),
            "sites": list(self.MON_CRASH_SITES),
            "paxos_torn_commit_repairs":
                self.store.counters["paxos_torn_commit_repairs"],
            "fsync_reorder_windows":
                self.store.counters["fsync_reorder_windows"],
        }
        return out

    # entity helpers -------------------------------------------------------

    @property
    def entity_name(self) -> str:
        return self.entity

    def _mon_monmap(self) -> MonMap:
        """MonMap keyed by entity names for the elector."""
        mm = MonMap(epoch=self.monmap.epoch, fsid=self.monmap.fsid)
        for n in self.monmap.ranks():
            mm.add(f"mon.{n}", self.monmap.addr_of(n))
        return mm

    def adopt_monmap(self, mm) -> None:
        """A newer monmap committed (MonmapMonitor): swap it in,
        rebuild the elector's roster, re-publish to subscribers, and —
        when the ROSTER actually changed — call a fresh election
        (Monitor::bootstrap on monmap change): a sitting leader must
        not keep committing under the old, smaller quorum rule, and a
        removed member must drop out.  Growing 1->2 therefore stalls
        the quorum until the new mon boots, exactly like the
        reference."""
        from .messages import MMonMap
        old_roster = set(self.monmap.ranks())
        self.monmap = mm
        self.elector.monmap = self._mon_monmap()
        self.log.info("adopted monmap e%d: %s", mm.epoch,
                      ",".join(mm.ranks()))
        for entity, sess in list(self.subs.items()):
            if "monmap" in sess["what"]:
                try:
                    self.msgr.send_message(MMonMap(monmap=mm.encode()),
                                           entity, sess["addr"])
                except Exception:
                    pass
        if self.name not in mm.mons:
            # we were removed: step down and stop participating — a
            # deposed leader must not keep acking commands while the
            # survivors elect a replacement (two-leader window), and
            # the elector cannot run with a roster that lacks us
            self.log.info("removed from monmap e%d: stepping down",
                          mm.epoch)
            self._removed = True
            self.elector.stop()       # cancels armed victory/restart
                                      # timers too — a mid-candidacy
                                      # removed mon must not win
            self.paxos.active = False
            return
        if set(mm.ranks()) != old_roster and self.msgr._loop is not None:
            # roster changed: force re-election (Monitor::bootstrap).
            # Skip during construction (messenger not started yet) —
            # Monitor.start() begins the election anyway.
            self.elector.start()

    def _send_mon(self, peer_entity: str, msg: Message) -> None:
        short = peer_entity.split(".", 1)[1]
        self.msgr.send_message(msg, peer_entity, self.monmap.addr_of(short))

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self.msgr.start()
        self.asok.start()
        with self.lock:
            self.elector.start()
        self._schedule_tick()

    def shutdown(self) -> None:
        self._stopped = True
        from ..utils import optracker
        optracker.recorder().unregister(self.entity)
        if self._tick_timer:
            self._tick_timer.cancel()
        self.asok.shutdown()
        self.msgr.shutdown()
        self.store.close()

    def abort(self) -> None:
        """kill -9 analog: freeze the store FIRST (no in-flight paxos
        txn lands another op, no clean teardown write happens), then
        tear the threads down — the store comes back exactly as the
        crash left it."""
        self.store.freeze()
        self.shutdown()

    def _on_store_crash(self, site: str) -> None:
        """A FaultSet crash rule fired inside our store (which is
        already frozen): simulated power loss.  Abort from a separate
        thread — the crashing paxos path is deep inside dispatch
        holding the monitor lock and must simply unwind via
        CrashPoint, never ack, never run the teardown itself."""
        if self._stopped:
            return
        self.log.warn("CRASH POINT %s fired: simulated power loss, "
                      "aborting", site)
        threading.Thread(target=self.abort, daemon=True,
                         name=f"{self.entity}-crash").start()

    def _schedule_tick(self) -> None:
        if self._stopped:
            return
        self._tick_timer = self.clock.timer(
            float(self.conf.mon_tick_interval), self._tick)

    def _tick(self) -> None:
        self._ticks += 1
        with self.lock:
            self.paxos.tick()
            if self.is_leader():
                self.osdmon.tick()
                self.paxos.maybe_trim()
            else:
                self._check_lease_timeout()
        self._schedule_tick()

    def _check_lease_timeout(self) -> None:
        """Peon leader-death detection (Paxos::lease_timeout ->
        bootstrap in the reference): a live leader renews leases every
        tick, so a lease a full mon_lease past its expiry means the
        leader is gone — call an election instead of sitting wedged
        forever forwarding commands to a dead address.  Without this,
        an abruptly killed leader (restart_mon, a paxos crash point)
        stalls the quorum until an operator intervenes."""
        p = self.paxos
        if (p.is_leader() or self.elector.electing or self._removed
                or self.monmap.size < 2):
            return
        if self.elector.leader is None or p.lease_expire <= 0:
            return
        overdue = self.clock.now() - p.lease_expire
        if overdue > float(self.conf.mon_lease):
            self.log.warn("leader %s lease expired %.1fs ago: "
                          "calling election", self.elector.leader,
                          overdue)
            p.lease_expire = 0.0     # one election per expiry window
            self.elector.start()

    # -- election ----------------------------------------------------------

    def is_leader(self) -> bool:
        return self.paxos.is_leader() and self.paxos.active

    def _won(self, epoch: int, quorum: list[str]) -> None:
        self.perf.inc("elections_won")
        rank = self.elector.rank
        self.paxos.leader_init(quorum, rank)

    def _lost(self, epoch: int, leader: str, quorum: list[str]) -> None:
        self.perf.inc("elections_lost")
        self.paxos.peon_init(leader, quorum, self.elector.rank)

    # -- paxos glue --------------------------------------------------------

    def propose_service(self, svc: PaxosService) -> None:
        """Collect the service's pending into a paxos value and propose."""
        if not self.paxos.is_writeable():
            # queue: re-proposed on activation; simplest correct behavior
            if svc not in self._proposing:
                self._proposing.append(svc)
            return
        ops: list = []
        svc.encode_pending(ops)
        svc.have_pending = False
        svc.pending = None
        self.paxos.propose(denc.dumps(ops))

    def _paxos_trace(self, event: str, version: int) -> None:
        """Paxos phase hook -> spans on tracked command ops.  Runs
        under self.lock (every paxos entry point holds it — a round
        begun during _execute_command fires this synchronously).
        paxos.propose covers the accept round (begin -> quorum
        accepted+applied); paxos.commit covers commit-visible ->
        client ack.  Commands batched into one proposal share the
        interval."""
        if event == "begin":
            for holder in self._cmd_ops:
                if holder[1] == "pending":
                    holder[0].span_begin("paxos.propose",
                                         version=version)
                    holder[1] = "propose"
        elif event == "commit":
            for holder in self._cmd_ops:
                if holder[1] == "propose":
                    holder[0].span_end("paxos.propose")
                    holder[0].span_begin("paxos.commit",
                                         version=version)
                    holder[1] = "commit"

    def _on_commit(self, version: int) -> None:
        for svc in self.services.values():
            svc.update_from_paxos()
        self._drain_proposing()
        if self.paxos.pending_value is None and \
                not self.paxos.proposals and not self._proposing:
            acks, self._pending_acks = self._pending_acks, []
            for origin, addr, tid, retval, out, data, holder in acks:
                if holder is not None:
                    trk, phase = holder
                    if phase == "commit":
                        trk.span_end("paxos.commit")
                    trk.mark_event("acked")
                    trk.finish()
                    if holder in self._cmd_ops:
                        self._cmd_ops.remove(holder)
                self._ack_to(origin, addr, tid, retval, out, data)

    def _drain_proposing(self) -> None:
        while self._proposing and self.paxos.is_writeable():
            svc = self._proposing.pop(0)
            if svc.have_pending:
                self.propose_service(svc)

    def _on_paxos_active(self) -> None:
        """The leader just became writeable: propose everything queued
        while it was recovering.  A service proposal accepted during
        the recovery window would otherwise sit in _proposing until
        the NEXT commit — and with no commit ever coming, an acked
        `mon add` could strand uncommitted forever (the
        grow-one-to-three membership race)."""
        self._drain_proposing()

    # -- publication -------------------------------------------------------

    def publish_osdmap(self) -> None:
        for entity, sess in list(self.subs.items()):
            want = sess["what"].get("osdmap")
            if want is None:
                continue
            self._send_osdmap_to(entity, sess["addr"], want)
            sess["what"]["osdmap"] = self.osdmon.osdmap.epoch + 1

    def _send_osdmap_to(self, entity: str, addr, since_epoch: int) -> None:
        cur = self.osdmon.osdmap
        if since_epoch > cur.epoch:
            return          # subscriber is current: renewal sends nothing
        if since_epoch <= 0:
            incs: list[bytes] = []
        else:
            incs = self.osdmon.get_incrementals(since_epoch - 1)
        if since_epoch <= 0 or (incs and len(incs) !=
                                cur.epoch - since_epoch + 1):
            msg = MOSDMapMsg(full=cur.encode(), incrementals=[],
                             epoch=cur.epoch)
        else:
            msg = MOSDMapMsg(full=None if incs else cur.encode(),
                             incrementals=incs, epoch=cur.epoch)
        self.msgr.send_message(msg, entity, addr)

    # -- dispatch ----------------------------------------------------------

    def ms_dispatch(self, conn, msg: Message) -> bool:
        with self.lock:
            return self._dispatch_locked(conn, msg)

    def _dispatch_locked(self, conn, msg: Message) -> bool:
        if self._removed:
            return True          # deposed: drop everything
        if isinstance(msg, MMonElection):
            self.elector.handle(msg)
            return True
        if isinstance(msg, MMonPaxos):
            self.paxos.handle(msg)
            return True
        if isinstance(msg, MMonSubscribe):
            self._handle_subscribe(conn, msg)
            return True
        if isinstance(msg, MMonCommand):
            self.perf.inc("commands")
            self._handle_command(conn, msg)
            return True
        if isinstance(msg, (MOSDBoot, MOSDFailure, MPGTemp, MMgrBeacon,
                            MMDSBeacon, MPGStats, MLogMsg)):
            # OSDMap mutations only mean anything on the leader; a peon
            # relays them (Monitor::forward_request_leader model).  The
            # session note stays local: the booting OSD subscribed to
            # *this* mon, and peons publish maps on commit too.
            if isinstance(msg, MOSDBoot) and \
                    not conn.peer_name.startswith("mon."):
                self._note_session(conn, {"osdmap": 0})
            if not self.is_leader():
                leader = self.elector.leader
                if leader is not None and leader != self.entity:
                    if isinstance(msg, MOSDFailure):
                        # src is re-stamped in transit; keep the reporter
                        msg.reporter = getattr(msg, "reporter", msg.src)
                    self._send_mon(leader, msg)
                return True
            if isinstance(msg, MOSDBoot):
                self.osdmon.handle_boot(msg.osd_id, msg.addr,
                                        getattr(msg, "heartbeat_addr", None))
            elif isinstance(msg, MOSDFailure):
                self.osdmon.handle_failure(
                    msg.target_osd, getattr(msg, "reporter", msg.src))
            elif isinstance(msg, MMgrBeacon):
                self.osdmon.handle_mgr_beacon(msg.name, msg.addr)
            elif isinstance(msg, MMDSBeacon):
                self.osdmon.handle_mds_beacon(
                    msg.name, msg.addr, getattr(msg, "rank", 0))
            elif isinstance(msg, MPGStats):
                self.osdmon.handle_pg_stats(msg.osd_id, msg.stats,
                                            getattr(msg, "epoch", 0),
                                            getattr(msg, "flags", None))
            elif isinstance(msg, MLogMsg):
                self.logmon.handle_log(msg)
            else:
                self.osdmon.handle_pg_temp(msg.osd_id, msg.pg_temp)
            return True
        return False

    def _note_session(self, conn, what: dict) -> None:
        sess = self.subs.setdefault(
            conn.peer_name, {"addr": conn.peer_addr, "what": {}})
        sess["addr"] = conn.peer_addr
        for k, v in what.items():
            sess["what"].setdefault(k, v)

    def _handle_subscribe(self, conn, msg: MMonSubscribe) -> None:
        sess = self.subs.setdefault(
            conn.peer_name, {"addr": conn.peer_addr, "what": {}})
        sess["addr"] = conn.peer_addr
        for name, start in msg.what.items():
            sess["what"][name] = start
            if name == "osdmap":
                self._send_osdmap_to(conn.peer_name, conn.peer_addr, start)
                sess["what"]["osdmap"] = self.osdmon.osdmap.epoch + 1
            elif name == "monmap":
                # epoch-gated like osdmap: a renewal claiming the
                # current epoch+1 costs nothing; a change pushes
                if self.monmap.epoch >= (start or 0):
                    self.msgr.send_message(
                        MMonMap(monmap=self.monmap.encode()),
                        conn.peer_name, conn.peer_addr)

    # -- commands ----------------------------------------------------------

    def _handle_command(self, conn, msg: MMonCommand) -> None:
        if not self.paxos.is_leader():
            leader = self.elector.leader
            if leader is None:
                self._ack(conn, msg.tid, -11, "no quorum", b"")
                return
            # forward to leader, remember where to send the reply.
            # fwd_origin is REAL wire data (the leader routes its ack
            # by it) — underscore-prefixed fields never leave the
            # process (Message.encode_iov skips them: they hold live
            # local objects like TrackedOp handles)
            fwd = MMonCommand(tid=msg.tid, cmd=msg.cmd,
                              fwd_origin=conn.peer_name,
                              fwd_origin_addr=conn.peer_addr)
            self._send_mon(leader, fwd)
            return
        origin = getattr(msg, "fwd_origin", None) or conn.peer_name
        origin_addr = getattr(msg, "fwd_origin_addr", None) \
            or conn.peer_addr
        in_flight_before = (self.paxos.pending_value is not None
                            or bool(self.paxos.proposals)
                            or bool(self._proposing))
        cmd = dict(msg.cmd)
        # the AUTHENTICATED peer identity, for commands that gate on
        # who is asking (rotating-key fetches); never client-supplied
        cmd["_requester"] = origin
        trk = self.op_tracker.create(
            f"mon_command {cmd.get('prefix', '?')} from {origin}",
            kind="command")
        # register BEFORE executing: a write command's paxos round can
        # begin synchronously inside _execute_command, and the tracer
        # hook must find this op to open its paxos.propose span
        holder = [trk, "pending"]
        self._cmd_ops.append(holder)
        trk.span_begin("execute")
        result = self._execute_command(cmd)
        trk.span_end("execute")
        if result is None:
            self._cmd_ops.remove(holder)
            trk.finish()
            self._ack_to(origin, origin_addr, msg.tid, -22,
                         f"unknown command {msg.cmd.get('prefix')!r}", b"")
            return
        retval, out, data = result
        # a proposal QUEUED for a recovering leader (self._proposing)
        # is a write too: acking it before the eventual commit would
        # let the client observe an ack whose effect can still vanish
        wrote = (self.paxos.pending_value is not None
                 or bool(self.paxos.proposals)
                 or bool(self._proposing) or in_flight_before)
        if wrote and retval == 0:
            # ack only after the commit lands so a follow-up read
            # observes the new state (wait_for_commit semantics); the
            # tracked op rides along, the paxos tracer hook stamping
            # its paxos.propose / paxos.commit spans as rounds pass
            self._pending_acks.append(
                (origin, origin_addr, msg.tid, retval, out, data,
                 holder))
        else:
            self._cmd_ops.remove(holder)
            trk.finish()
            self._ack_to(origin, origin_addr, msg.tid, retval, out, data)

    def _execute_command(self, cmd: dict):
        if cmd.get("prefix") == "status":
            return self._cmd_status()
        for svc in self.services.values():
            result = svc.dispatch_command(cmd)
            if result is not None:
                return result
        return None

    def _cmd_status(self):
        """`ceph -s` analog: health + mon/osd/pg summaries."""
        m = self.osdmon.osdmap
        up = sum(1 for o in m.osds.values() if o.up)
        inn = sum(1 for o in m.osds.values() if o.in_cluster)
        status, warns = self.osdmon.health()
        lines = [f"health: {status}"]
        lines += [f"  {w}" for w in warns]
        lines += [
            f"mon: {self.monmap.size} mons, quorum "
            f"{self.elector.quorum}",
            f"osd: {len(m.osds)} osds: {up} up, {inn} in; epoch "
            f"{m.epoch}",
            f"pools: {len(m.pools)}",
        ]
        summary = self.osdmon.pg_summary()
        if summary:
            pgs = ", ".join(f"{n} {state}" for state, n
                            in sorted(summary.items()))
            lines.append(f"pgs: {sum(summary.values())} total: {pgs}")
        return 0, "\n".join(lines), b""

    def _ack(self, conn, tid, retval, out, data) -> None:
        self._ack_to(conn.peer_name, conn.peer_addr, tid, retval, out, data)

    def _ack_to(self, entity, addr, tid, retval, out, data=b"") -> None:
        self.msgr.send_message(
            MMonCommandAck(tid=tid, retval=retval, out=out, data=data),
            entity, addr)

    def ms_handle_reset(self, conn) -> None:
        self.subs.pop(conn.peer_name, None)


def make_fsid() -> str:
    return str(uuid.uuid4())
