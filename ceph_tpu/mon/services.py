"""PaxosService framework + OSDMonitor (mon/PaxosService.h, OSDMonitor.cc).

Each service keeps versioned state in the shared MonitorDBStore under
its own prefix and folds its pending changes into the single Paxos
value when the monitor proposes.  OSDMonitor manages the OSDMap:
boot/failure/out transitions, pool + EC-profile commands (validated by
instantiating the erasure plugin, OSDMonitor.cc:6291 semantics), map
publication to subscribers.
"""

from __future__ import annotations

from ..utils import denc
from typing import TYPE_CHECKING

from ..erasure.interface import ErasureCodeError
from ..erasure.registry import registry as ec_registry
from ..osd.osdmap import (ERASURE, REPLICATED, OSDMap, OSDMapIncremental,
                          PgId, Pool)
from ..utils.dout import DoutLogger

if TYPE_CHECKING:
    from .monitor import Monitor


class PaxosService:
    name = "base"

    def __init__(self, mon: "Monitor"):
        self.mon = mon
        self.log = DoutLogger(self.name, mon.name)
        self.have_pending = False

    @property
    def version(self) -> int:
        return self.mon.store.get_int(self.name, "last_committed")

    def update_from_paxos(self) -> None:
        """Replay any committed versions we have not absorbed yet."""
        raise NotImplementedError

    def create_pending(self) -> None:
        raise NotImplementedError

    def encode_pending(self, txn_ops: list) -> None:
        """Append ('set', prefix, key, blob) KV ops for the pending state."""
        raise NotImplementedError

    def propose_pending(self) -> None:
        self.mon.propose_service(self)

    def dispatch_command(self, cmd: dict) -> tuple[int, str, bytes] | None:
        """(retval, out_text, out_data) or None if not ours / deferred."""
        return None


class OSDMonitor(PaxosService):
    name = "osdmap"

    def __init__(self, mon: "Monitor"):
        super().__init__(mon)
        self.osdmap = OSDMap()
        self.pending: OSDMapIncremental | None = None
        self._last_proposed_epoch = 0
        # failure_reports[target] = {reporter: first_report_time}
        self.failure_reports: dict[int, dict[str, float]] = {}
        self.down_at: dict[int, float] = {}
        # PGMap-lite (mon/PGMonitor.cc): pgid -> latest primary-
        # reported stat dict; leader-local, repopulated within one
        # osd stats interval after an election
        self.pg_stats: dict[str, dict] = {}
        # per-osd health flags riding the stats reports (e.g. a
        # device-degraded EC codec); leader-local like pg_stats
        self.osd_health_flags: dict[int, dict] = {}
        # rank -> last MDS beacon time; ranks silent past
        # mds_beacon_grace are dropped from the map so clients stop
        # routing to dead addresses (FSMap failed-rank analog)
        self.mds_last_beacon: dict[int, float] = {}
        self._replay()

    # -- state machinery ---------------------------------------------------

    def _replay(self) -> None:
        v = self.version
        while self.osdmap.epoch < v:
            blob = self.mon.store.get_version(self.name, self.osdmap.epoch + 1)
            if blob is None:
                break
            self.osdmap.apply_incremental(denc.loads(blob))

    def update_from_paxos(self) -> None:
        before = self.osdmap.epoch
        self._replay()
        if self.osdmap.epoch != before:
            self.have_pending = False
            self.pending = None
            self.mon.publish_osdmap()

    def create_pending(self) -> None:
        # a prior pending inc may still be in flight through paxos;
        # epochs must stay strictly increasing across proposals
        epoch = max(self.osdmap.epoch, self._last_proposed_epoch) + 1
        self.pending = OSDMapIncremental(epoch=epoch)
        self.have_pending = True

    def _pending(self) -> OSDMapIncremental:
        if not self.have_pending or self.pending is None:
            self.create_pending()
        return self.pending

    def encode_pending(self, txn_ops: list) -> None:
        inc = self.pending
        blob = denc.dumps(inc)
        vkey = f"{inc.epoch:020d}"
        txn_ops.append(("set", self.name, vkey, blob))
        txn_ops.append(("set", self.name, "last_committed",
                        str(inc.epoch).encode()))
        self._last_proposed_epoch = inc.epoch

    def get_incrementals(self, since: int) -> list[bytes]:
        out = []
        for v in range(since + 1, self.osdmap.epoch + 1):
            blob = self.mon.store.get_version(self.name, v)
            if blob is not None:
                out.append(blob)
        return out

    # -- osd lifecycle -----------------------------------------------------

    def handle_boot(self, osd_id: int, addr, hb_addr=None) -> None:
        if self.osdmap.is_up(osd_id) and \
                self.osdmap.get_addr(osd_id) == tuple(addr):
            return
        inc = self._pending()
        inc.new_up[osd_id] = tuple(addr)
        self.failure_reports.pop(osd_id, None)
        self.down_at.pop(osd_id, None)
        self.log.info("osd.%d booting at %s", osd_id, addr)
        self._cluster_log("INF", f"osd.{osd_id} boot")
        self.propose_pending()

    def _cluster_log(self, level: str, text: str) -> None:
        logmon = getattr(self.mon, "logmon", None)
        if logmon is not None:
            logmon.log_entry("mon", level, text)

    def handle_failure(self, target: int, reporter: str) -> None:
        if not self.osdmap.is_up(target):
            return
        reports = self.failure_reports.setdefault(target, {})
        reports[reporter] = self.mon.clock.now()
        need = int(self.mon.conf.mon_osd_min_down_reporters)
        if len(reports) >= need:
            inc = self._pending()
            if target not in inc.new_down:
                inc.new_down.append(target)
                self.down_at[target] = self.mon.clock.now()
                self.log.info("marking osd.%d down (%d reporters)",
                              target, len(reports))
                self._cluster_log(
                    "WRN", f"osd.{target} marked down "
                           f"({len(reports)} reporters)")
                self.failure_reports.pop(target, None)
                self.propose_pending()

    def handle_mgr_beacon(self, name: str, addr) -> None:
        """Active-mgr registration (MgrMonitor folded into the osdmap:
        the beacon publishes where daemons should send MMgrReport)."""
        if self.osdmap.mgr_name == name and \
                self.osdmap.mgr_addr == tuple(addr):
            return
        inc = self._pending()
        inc.new_mgr = (name, tuple(addr))
        self.log.info("mgr %s active at %s", name, addr)
        self.propose_pending()

    def handle_mds_beacon(self, name: str, addr, rank: int = 0) -> None:
        """Active-mds registration (FSMap folded into the osdmap);
        each rank registers independently (multi-rank FSMap)."""
        # record liveness even when the map already has this rank —
        # the early return below must not starve the beacon clock
        self.mds_last_beacon[rank] = self.mon.clock.now()
        if self.osdmap.mds_ranks.get(rank) == (name, tuple(addr)):
            return
        inc = self._pending()
        inc.new_mds_ranks = dict(inc.new_mds_ranks)
        inc.new_mds_ranks[rank] = (name, tuple(addr))
        if rank == 0:
            inc.new_mds = (name, tuple(addr))
        self.log.info("mds %s rank %d active at %s", name, rank, addr)
        self.propose_pending()

    def handle_pg_temp(self, osd_id: int, pg_temp: dict) -> None:
        inc = self._pending()
        changed = False
        for pgid_str, osds in pg_temp.items():
            pgid = PgId.parse(pgid_str)
            cur = self.osdmap.pg_temp.get(pgid, [])
            if list(osds) != cur:
                inc.new_pg_temp[pgid] = list(osds)
                changed = True
        if changed:
            self.propose_pending()

    def _prune_stale_mds_ranks(self, now: float) -> None:
        """Drop mds_ranks entries whose daemon stopped beaconing: a
        dead rank left in the map keeps routing that subtree's client
        ops to a dead address until an operator intervenes (the
        reference FSMap marks such ranks failed)."""
        grace = float(self.mon.conf.mds_beacon_grace)
        if grace <= 0:
            return
        changed = False
        for rank in list(self.osdmap.mds_ranks):
            # seed on first sight so a fresh leader (empty beacon
            # clock) never insta-prunes a live rank
            last = self.mds_last_beacon.setdefault(rank, now)
            if now - last <= grace:
                continue
            inc = self._pending()
            if rank in inc.new_mds_ranks and \
                    inc.new_mds_ranks[rank] is None:
                continue            # prune already pending
            inc.new_mds_ranks = dict(inc.new_mds_ranks)
            inc.new_mds_ranks[rank] = None
            self.mds_last_beacon.pop(rank, None)
            changed = True
            self.log.warn("mds rank %d silent for %.0fs, removing "
                          "from map", rank, now - last)
            self._cluster_log(
                "WRN", f"mds rank {rank} silent past beacon grace; "
                       f"removed from map")
        if changed:
            self.propose_pending()

    def tick(self) -> None:
        """Auto-out for long-down OSDs + stale-MDS pruning."""
        self._prune_stale_mds_ranks(self.mon.clock.now())
        interval = float(self.mon.conf.mon_osd_down_out_interval)
        if interval <= 0:
            return
        now = self.mon.clock.now()
        changed = False
        for osd, t in list(self.down_at.items()):
            if (now - t > interval and self.osdmap.is_in(osd)
                    and not self.osdmap.is_up(osd)):
                inc = self._pending()
                if osd not in inc.new_out:
                    inc.new_out.append(osd)
                    changed = True
                    self.down_at.pop(osd)
                    self.log.info("marking osd.%d out after %ds down",
                                  osd, int(now - t))
                    self._cluster_log(
                        "WRN", f"osd.{osd} marked out after "
                               f"{int(now - t)}s down")
        if changed:
            self.propose_pending()

    # -- commands ----------------------------------------------------------

    def dispatch_command(self, cmd: dict):
        prefix = cmd.get("prefix", "")
        if prefix == "osd pool create":
            return self._cmd_pool_create(cmd)
        if prefix == "osd pool rm":
            return self._cmd_pool_rm(cmd)
        if prefix == "osd pool ls":
            names = [p.name for p in self.osdmap.pools.values()]
            return 0, "\n".join(names), b""
        if prefix == "osd erasure-code-profile set":
            return self._cmd_ec_profile_set(cmd)
        if prefix == "osd erasure-code-profile get":
            name = cmd.get("name", "")
            prof = self.osdmap.ec_profiles.get(name)
            if prof is None:
                return -2, f"no such profile {name}", b""
            text = "\n".join(f"{k}={v}" for k, v in sorted(prof.items()))
            return 0, text, b""
        if prefix == "osd erasure-code-profile ls":
            return 0, "\n".join(sorted(self.osdmap.ec_profiles)), b""
        if prefix == "osd erasure-code-profile rm":
            return self._cmd_ec_profile_rm(cmd)
        if prefix == "osd dump":
            return 0, self._dump_text(), self.osdmap.encode()
        if prefix == "osd getmap":
            return 0, "", self.osdmap.encode()
        if prefix == "osd tree":
            return 0, self._tree_text(), b""
        if prefix == "osd pool selfmanaged-snap create":
            return self._cmd_snap_create(cmd)
        if prefix == "osd pool selfmanaged-snap rm":
            return self._cmd_snap_rm(cmd)
        if prefix in ("osd down", "osd out", "osd in"):
            return self._cmd_osd_state(prefix, cmd)
        if prefix.startswith("osd tier "):
            return self._cmd_tier(prefix, cmd)
        if prefix == "osd pool set":
            return self._cmd_pool_set(cmd)
        if prefix == "osd rm-pg-temp":
            # a primary finished backfilling the CRUSH targets of a
            # temp-pinned pg: release the pin (empty list = removal)
            from ..osd.osdmap import PgId
            try:
                pgid = PgId.parse(cmd.get("pgid", ""))
            except Exception:
                return -22, f"bad pgid {cmd.get('pgid')!r}", b""
            if pgid not in self.osdmap.pg_temp:
                return 0, f"no pg_temp for {pgid}", b""
            self._pending().new_pg_temp[pgid] = []
            self.propose_pending()
            return 0, f"removed pg_temp for {pgid}", b""
        if prefix == "osd reweight":
            inc = self._pending()
            inc.new_weights[int(cmd["id"])] = float(cmd["weight"])
            self.propose_pending()
            return 0, f"reweighted osd.{cmd['id']}", b""
        if prefix in ("pg scrub", "pg deep-scrub", "pg repair"):
            return self._cmd_pg_scrub(prefix, cmd)
        if prefix == "health":
            status, warns = self.health()
            return 0, "\n".join([status] + [f"  {w}" for w in warns]), b""
        if prefix == "pg dump":
            import json
            lines = [f"{pgid} {st.get('state', '?')} "
                     f"objects={st.get('objects', 0)} "
                     f"osd.{st.get('reported_by')}"
                     for pgid, st in sorted(self.pg_stats.items())]
            return 0, "\n".join(lines), json.dumps(
                self.pg_stats, default=str).encode()
        return None

    def _cmd_pg_scrub(self, prefix: str, cmd: dict):
        """Instruct a pg's primary to scrub/repair (the reference's
        `ceph pg repair` -> OSDMonitor -> MOSDScrub to the primary;
        execution is asynchronous on the OSD)."""
        from ..osd.messages import MOSDScrub
        from ..osd.osdmap import PgId
        pgid_s = cmd.get("pgid", "")
        try:
            pgid = PgId.parse(pgid_s)
        except Exception:
            return -22, f"bad pgid {pgid_s!r}", b""
        if pgid.pool not in self.osdmap.pools:
            return -2, f"no pool for pg {pgid_s}", b""
        primary = self.osdmap.pg_primary(pgid)
        if primary is None:
            return -11, f"pg {pgid_s} has no primary", b""
        addr = self.osdmap.get_addr(primary)
        if addr is None:
            return -11, f"osd.{primary} has no address", b""
        self.mon.msgr.send_message(
            MOSDScrub(pgid=pgid_s, deep=prefix != "pg scrub",
                      repair=prefix == "pg repair"),
            f"osd.{primary}", tuple(addr))
        verb = prefix.split(" ", 1)[1].replace("-", " ")
        return 0, f"instructing pg {pgid_s} on osd.{primary} to {verb}", b""

    def _cmd_pool_create(self, cmd: dict):
        name = cmd.get("pool", "")
        if not name:
            return -22, "pool name required", b""
        if self.osdmap.pool_by_name(name):
            return 0, f"pool '{name}' already exists", b""
        pg_num = int(cmd.get("pg_num",
                             self.mon.conf.osd_pool_default_pg_num))
        pool_type = cmd.get("pool_type", "replicated")
        pid = self.osdmap.pool_max + 1
        pending_pools = self._pending().new_pools
        while pid in pending_pools or pid in self.osdmap.pools:
            pid += 1
        pool = Pool(id=pid, name=name, pg_num=pg_num)
        if pool_type == "erasure":
            profile_name = cmd.get("erasure_code_profile", "default")
            profile = dict(self.osdmap.ec_profiles.get(profile_name, {}))
            for k, v in self._pending().new_ec_profiles.get(
                    profile_name, {}).items():
                profile[k] = v
            if not profile and profile_name == "default":
                profile = {"plugin": "tpu", "technique": "reed_sol_van",
                           "k": "2", "m": "1"}
                self._pending().new_ec_profiles["default"] = profile
            if not profile:
                return -2, f"no erasure profile {profile_name}", b""
            try:
                codec = ec_registry.factory(
                    profile.get("plugin", "tpu"), profile)
            except ErasureCodeError as e:
                return -22, f"bad profile: {e}", b""
            k = codec.get_data_chunk_count()
            km = codec.get_chunk_count()
            pool.type = ERASURE
            pool.size = km
            pool.min_size = k + 1 if km > k + 1 else k
            pool.erasure_code_profile = profile_name
            # each EC pool gets an indep crush rule; mutate a COPY so
            # the committed map only changes when the inc commits
            import copy
            crush = copy.deepcopy(self.osdmap.crush)
            rid = crush.make_erasure_rule(f"ec-{name}", k, km - k)
            pool.crush_ruleset = rid
            self._pending().new_crush = denc.dumps(crush)
        else:
            pool.type = REPLICATED
            pool.size = int(cmd.get("size",
                                    self.mon.conf.osd_pool_default_size))
            pool.min_size = max(1, pool.size - pool.size // 2)
        self._pending().new_pools[pid] = pool
        self.propose_pending()
        return 0, f"pool '{name}' created", b""

    def _cmd_pool_rm(self, cmd: dict):
        name = cmd.get("pool", "")
        pool = self.osdmap.pool_by_name(name)
        if pool is None:
            return -2, f"no such pool {name}", b""
        self._pending().removed_pools.append(pool.id)
        self.propose_pending()
        return 0, f"pool '{name}' removed", b""

    def _cmd_ec_profile_set(self, cmd: dict):
        name = cmd.get("name", "")
        profile = {}
        for tok in cmd.get("profile", []):
            if "=" not in tok:
                return -22, f"bad profile entry {tok!r}", b""
            k, v = tok.split("=", 1)
            profile[k] = v
        profile.setdefault("plugin", "tpu")
        # validate by instantiating (OSDMonitor.cc:6291 behavior)
        try:
            ec_registry.factory(profile["plugin"], profile)
        except ErasureCodeError as e:
            return -22, f"invalid profile: {e}", b""
        if (name in self.osdmap.ec_profiles
                and self.osdmap.ec_profiles[name] != profile
                and not cmd.get("force")):
            return -1, f"profile {name} exists; use force to override", b""
        self._pending().new_ec_profiles[name] = profile
        self.propose_pending()
        return 0, "", b""

    def _cmd_ec_profile_rm(self, cmd: dict):
        name = cmd.get("name", "")
        for pool in self.osdmap.pools.values():
            if pool.erasure_code_profile == name:
                return -16, f"profile {name} in use by pool {pool.name}", b""
        inc = self._pending()
        inc.new_ec_profiles[name] = None   # tombstone
        self.propose_pending()
        return 0, "", b""

    def _cmd_snap_create(self, cmd: dict):
        """Allocate a self-managed snap id (pool snap_seq bump; the
        librados selfmanaged_snap_create / OSDMonitor pool snap path)."""
        pool = self.osdmap.pool_by_name(cmd.get("pool", ""))
        if pool is None:
            return -2, f"no such pool {cmd.get('pool')!r}", b""
        inc = self._pending()
        cur = inc.new_pool_snap_seq.get(pool.id, pool.snap_seq)
        snapid = cur + 1
        inc.new_pool_snap_seq[pool.id] = snapid
        self.propose_pending()
        return 0, str(snapid), denc.dumps(snapid)

    def _cmd_snap_rm(self, cmd: dict):
        pool = self.osdmap.pool_by_name(cmd.get("pool", ""))
        if pool is None:
            return -2, f"no such pool {cmd.get('pool')!r}", b""
        snapid = int(cmd.get("snapid", 0))
        if snapid <= 0 or snapid > pool.snap_seq:
            return -22, f"invalid snapid {snapid}", b""
        inc = self._pending()
        inc.new_removed_snaps.setdefault(pool.id, [])
        if snapid not in inc.new_removed_snaps[pool.id]:
            inc.new_removed_snaps[pool.id].append(snapid)
        self.propose_pending()
        return 0, f"removed snap {snapid}", b""

    def _cmd_osd_state(self, prefix: str, cmd: dict):
        osd = int(cmd["id"])
        inc = self._pending()
        if prefix == "osd down":
            inc.new_down.append(osd)
            self.down_at[osd] = self.mon.clock.now()
        elif prefix == "osd out":
            inc.new_out.append(osd)
        else:
            inc.new_in.append(osd)
        self.propose_pending()
        return 0, f"{prefix} osd.{osd}", b""

    # -- PGMap / health (PGMonitor + HealthMonitor reduced) ----------------

    def handle_pg_stats(self, osd_id: int, stats: dict,
                        epoch: int = 0,
                        flags: dict | None = None) -> None:
        now = self.mon.clock.now()
        if flags:
            # leased, not latched: a degraded daemon re-sends its
            # flags every stats report, so a daemon that dies or
            # restarts clean (and may then hold no primary pgs to
            # report about) ages out instead of warning forever
            self.osd_health_flags[osd_id] = {"flags": dict(flags),
                                             "at": now}
        else:
            self.osd_health_flags.pop(osd_id, None)
        for pgid, st in stats.items():
            cur = self.pg_stats.get(pgid)
            if cur is not None and cur.get("epoch", 0) > epoch:
                continue   # a stale ex-primary must not overwrite the
                           # current primary's report (PGMonitor gates
                           # on the reported epoch the same way)
            st = dict(st)
            st["reported_by"] = osd_id
            st["reported_at"] = now
            st["epoch"] = epoch
            self.pg_stats[pgid] = st
        # drop ghosts of deleted pools — they would pad the pg counts
        # and suppress the "not yet reported" warning forever
        pools = set(self.osdmap.pools)
        for pgid in list(self.pg_stats):
            try:
                pool_id = int(pgid.split(".", 1)[0])
            except ValueError:
                pool_id = -1
            if pool_id not in pools:
                del self.pg_stats[pgid]

    def pg_summary(self) -> dict[str, int]:
        """{state_string: count} over the latest reports."""
        out: dict[str, int] = {}
        for st in self.pg_stats.values():
            out[st.get("state", "unknown")] = \
                out.get(st.get("state", "unknown"), 0) + 1
        return out

    def health(self) -> tuple[str, list[str]]:
        """(HEALTH_OK|HEALTH_WARN, detail lines) — the `ceph -s`
        health block (mon/HealthMonitor.cc + PGMap::get_health)."""
        warns: list[str] = []
        m = self.osdmap
        down = [o for o, info in m.osds.items()
                if info.in_cluster and not info.up]
        if down:
            warns.append(f"{len(down)} osds down")
        total_pgs = sum(p.pg_num for p in m.pools.values())
        degraded = {s: n for s, n in self.pg_summary().items()
                    if "degraded" in s or "undersized" in s
                    or "peering" in s or "incomplete" in s}
        for state, n in sorted(degraded.items()):
            warns.append(f"{n} pgs {state}")
        if total_pgs and len(self.pg_stats) < total_pgs:
            warns.append(
                f"{total_pgs - len(self.pg_stats)} pgs not yet "
                f"reported")
        quorum = self.mon.elector.quorum
        if quorum and len(quorum) < self.mon.monmap.size:
            warns.append(f"{self.mon.monmap.size - len(quorum)}/"
                         f"{self.mon.monmap.size} mons out of quorum")
        now = self.mon.clock.now()
        for osd_id, ent in sorted(self.osd_health_flags.items()):
            if not m.is_up(osd_id) or now - ent.get("at", 0) > 60.0:
                continue   # dead/stale reporter: lease expired
            profiles = ent["flags"].get("ec_device_degraded")
            if profiles:
                warns.append(
                    f"osd.{osd_id} EC device degraded "
                    f"(matrix-codec fallback: "
                    f"{', '.join(profiles)})")
            quarantined = ent["flags"].get("ec_device_quarantined")
            if quarantined:
                warns.append(
                    f"osd.{osd_id} EC pipeline {quarantined} devices "
                    f"quarantined (redraining to surviving chips)")
            store_health = ent["flags"].get("store_health")
            if store_health:
                warns.append(f"osd.{osd_id} object store: "
                             f"{store_health}")
            slow = ent["flags"].get("slow_ops")
            if slow:
                # the reference's exact health line (OSDMap/PGMap slow
                # request warnings): level-triggered — the daemon
                # drops the flag once the ops complete, so the warn
                # clears with the next lease/report cycle
                warns.append(
                    f"{slow['count']} slow ops, oldest blocked for "
                    f"{slow['oldest']:.0f}s (osd.{osd_id})")
        return ("HEALTH_WARN" if warns else "HEALTH_OK"), warns

    # -- cache tiering commands (OSDMonitor "osd tier *" handlers) ---------

    def _pool_for_update(self, name: str):
        """Staged-or-committed pool by name, deep-copied for mutation;
        the copy goes into the pending incremental's new_pools."""
        import copy
        for p in self._pending().new_pools.values():
            if p.name == name:
                return p                   # already staged: mutate it
        pool = self.osdmap.pool_by_name(name)
        if pool is None:
            return None
        staged = copy.deepcopy(pool)
        self._pending().new_pools[pool.id] = staged
        return staged

    def _cmd_tier(self, prefix: str, cmd: dict):
        base = self._pool_for_update(cmd.get("pool", ""))
        if base is None:
            return -2, f"no such pool {cmd.get('pool')!r}", b""
        if prefix == "osd tier add":
            tier = self._pool_for_update(cmd.get("tierpool", ""))
            if tier is None:
                return -2, f"no such pool {cmd.get('tierpool')!r}", b""
            if tier is base:
                return -22, "a pool cannot tier itself", b""
            if tier.tier_of >= 0 or tier.tiers:
                return -22, f"{tier.name} is already involved in tiering", b""
            if base.tier_of >= 0:
                # no tier chains: the single-level objecter overlay
                # redirect and PG promote/flush logic cannot follow
                # a->b->c (OSDMonitor _check_become_tier forbids this)
                return -22, f"{base.name} is itself a cache tier", b""
            if tier.is_erasure:
                return -22, "cache pool must be replicated", b""
            tier.tier_of = base.id
            base.tiers = sorted(set(base.tiers) | {tier.id})
            self.propose_pending()
            return 0, f"pool {tier.name} is now a tier of {base.name}", b""
        if prefix == "osd tier cache-mode":
            mode = cmd.get("mode", "")
            if mode not in ("none", "writeback", "readonly"):
                return -22, f"bad cache-mode {mode!r}", b""
            if base.tier_of < 0:
                return -22, f"{base.name} is not a cache tier", b""
            base.cache_mode = mode
            self.propose_pending()
            return 0, f"cache-mode of {base.name} is now {mode}", b""
        if prefix == "osd tier set-overlay":
            tier = self._pool_for_update(cmd.get("overlaypool", ""))
            if tier is None or tier.tier_of != base.id:
                return -22, "overlay pool must be a tier of the base", b""
            base.read_tier = tier.id
            base.write_tier = tier.id
            self.propose_pending()
            return 0, f"overlay for {base.name} is now {tier.name}", b""
        if prefix == "osd tier remove-overlay":
            base.read_tier = -1
            base.write_tier = -1
            self.propose_pending()
            return 0, f"removed overlay for {base.name}", b""
        if prefix == "osd tier remove":
            tier = self._pool_for_update(cmd.get("tierpool", ""))
            if tier is None or tier.tier_of != base.id:
                return -22, "not a tier of that pool", b""
            if base.read_tier == tier.id or base.write_tier == tier.id:
                return -16, "remove the overlay first", b""   # EBUSY
            tier.tier_of = -1
            tier.cache_mode = "none"
            base.tiers = [t for t in base.tiers if t != tier.id]
            self.propose_pending()
            return 0, f"pool {tier.name} is no longer a tier", b""
        return -22, f"unknown tier command {prefix!r}", b""

    _POOL_SET_VARS = {
        "size": int, "min_size": int, "hit_set_count": int,
        "hit_set_period": float, "target_max_objects": int,
        "pg_num": int,
    }

    def _cmd_pool_set(self, cmd: dict):
        pool = self._pool_for_update(cmd.get("pool", ""))
        if pool is None:
            return -2, f"no such pool {cmd.get('pool')!r}", b""
        var = cmd.get("var", "")
        caster = self._POOL_SET_VARS.get(var)
        if caster is None:
            return -22, f"unknown pool variable {var!r}", b""
        try:
            val = caster(cmd.get("val", ""))
        except (TypeError, ValueError) as e:
            return -22, f"bad value for {var}: {e}", b""
        # range/consistency guards (OSDMonitor prepare_command_pool_set):
        # a committed min_size > size would EAGAIN every PG forever
        if var == "size" and not 1 <= val <= 10:
            return -22, f"size {val} out of range", b""
        if var == "size" and pool.min_size > val:
            return -22, f"size {val} < min_size {pool.min_size}", b""
        if var == "min_size" and not 1 <= val <= pool.size:
            return -22, (f"min_size {val} out of range "
                         f"[1, size={pool.size}]"), b""
        if var == "hit_set_period" and val <= 0:
            return -22, "hit_set_period must be > 0", b""
        if var == "hit_set_count" and val < 1:
            return -22, "hit_set_count must be >= 1", b""
        if var == "target_max_objects" and val < 0:
            return -22, "target_max_objects must be >= 0", b""
        if var == "pg_num":
            return self._cmd_pool_set_pg_num(pool, val)
        setattr(pool, var, val)
        self.propose_pending()
        return 0, f"set pool {pool.name} {var}", b""

    def _cmd_pool_set_pg_num(self, pool, val: int):
        """PG split: pg_num may only GROW (mon/OSDMonitor.cc:3649 —
        'specified pg_num must be > current'; merge does not exist in
        the reference either).  Each new child pg starts pinned via
        pg_temp to its PARENT's current acting set: the parent's OSDs
        split their local collections in place, so the children are
        immediately served from where the data already is; the
        primaries then backfill the CRUSH-computed targets and release
        the pg_temp pin (the reference's split + pg_temp/backfill
        flow, osd/OSD.cc:7553 split_pgs)."""
        # validate against the PENDING value: a second command in the
        # same uncommitted round must not slip a shrink past the guard
        old_num = pool.pg_num
        if val <= old_num:
            return -22, (f"specified pg_num {val} <= current "
                         f"{old_num}"), b""
        from ..osd.osdmap import PgId, parent_seed
        inc = self._pending()
        for child in range(old_num, val):
            parent = PgId(pool.id, parent_seed(child, old_num))
            _up, acting = self.osdmap.pg_to_up_acting_osds(parent)
            if acting:
                inc.new_pg_temp[PgId(pool.id, child)] = list(acting)
        pool.pg_num = val
        self.propose_pending()
        return 0, (f"set pool {pool.name} pg_num to {val} "
                   f"({val - old_num} pgs splitting)"), b""

    def _dump_text(self) -> str:
        m = self.osdmap
        lines = [f"epoch {m.epoch}", f"max_osd {m.max_osd}"]
        for pid, pool in sorted(m.pools.items()):
            kind = "erasure" if pool.is_erasure else "replicated"
            tier = ""
            if pool.tier_of >= 0:
                tier = f" tier_of {pool.tier_of} cache_mode {pool.cache_mode}"
            if pool.read_tier >= 0 or pool.write_tier >= 0:
                tier += (f" read_tier {pool.read_tier}"
                         f" write_tier {pool.write_tier}")
            lines.append(
                f"pool {pid} '{pool.name}' {kind} size {pool.size} "
                f"min_size {pool.min_size} pg_num {pool.pg_num}{tier}")
        for osd in sorted(m.osds):
            info = m.osds[osd]
            state = ("up" if info.up else "down") + \
                (" in" if info.in_cluster else " out")
            lines.append(f"osd.{osd} {state} weight {info.weight} "
                         f"addr {info.addr}")
        return "\n".join(lines)

    def _tree_text(self) -> str:
        lines = []
        for b in sorted(self.osdmap.crush.buckets.values(),
                        key=lambda b: -b.id):
            lines.append(f"{b.id}\t{b.name or '(bucket)'}")
            for item, w in zip(b.items, b.weights):
                lines.append(f"\t{item}\t{w / 0x10000:.3f}")
        return "\n".join(lines)


class MonmapMonitor(PaxosService):
    """Monitor-roster membership through paxos (mon/MonmapMonitor.cc:
    320 prepare_command `mon add`/`mon remove`): each committed version
    stores the FULL monmap at its new epoch; every mon adopts it on
    commit (Monitor.adopt_monmap rebuilds the elector roster and
    re-publishes to monmap subscribers), and a freshly-seeded mon that
    joins with an empty store pulls history via the paxos full-sync
    path and replays the latest monmap from it."""
    name = "monmap"

    def __init__(self, mon: "Monitor"):
        super().__init__(mon)
        self.pending = None
        self._last_proposed_epoch = 0
        self.update_from_paxos()

    def update_from_paxos(self) -> None:
        from .monmap import MonMap
        v = self.version
        if v <= self.mon.monmap.epoch:
            return
        blob = self.mon.store.get_version(self.name, v)
        if blob is None:
            return
        mm = MonMap.decode(blob)
        if mm.epoch > self.mon.monmap.epoch:
            self.mon.adopt_monmap(mm)

    def create_pending(self) -> None:
        # pending is a list of OPERATIONS, rebased onto the CURRENT
        # monmap at encode time: a queued proposal built while an
        # earlier one was still in flight must neither reuse its epoch
        # nor resurrect its pre-commit roster (the OSDMonitor
        # incremental + _last_proposed_epoch pattern)
        self.pending_ops: list[tuple] = []
        self.have_pending = True

    def encode_pending(self, txn_ops: list) -> None:
        mm = self.mon.monmap.copy()
        for op in self.pending_ops:
            if op[0] == "add":
                mm.add(op[1], op[2])
            else:
                mm.remove(op[1])
        mm.epoch = max(self.mon.monmap.epoch,
                       self._last_proposed_epoch) + 1
        self.pending_ops = []
        txn_ops.append(("set", self.name, f"{mm.epoch:020d}",
                        mm.encode()))
        txn_ops.append(("set", self.name, "last_committed",
                        str(mm.epoch).encode()))
        self._last_proposed_epoch = mm.epoch

    def _effective_roster(self) -> dict:
        mm = self.mon.monmap.copy()
        for op in getattr(self, "pending_ops", []):
            if op[0] == "add":
                mm.add(op[1], op[2])
            else:
                mm.remove(op[1])
        return mm.mons

    def _pending(self) -> list:
        if not self.have_pending or not hasattr(self, "pending_ops"):
            self.create_pending()
        return self.pending_ops

    def dispatch_command(self, cmd: dict):
        prefix = cmd.get("prefix")
        if prefix == "mon add":
            name = str(cmd.get("name", ""))
            addr = cmd.get("addr")
            if not name or not addr or len(tuple(addr)) != 2:
                return -22, "usage: mon add <name> <host:port>", b""
            ops = self._pending()
            roster = self._effective_roster()
            if name in roster:
                # idempotent for retries: a client whose first attempt
                # is still waiting out the commit may resend; the same
                # name at the same address is success, not EEXIST
                if tuple(roster[name]) == (str(addr[0]), int(addr[1])):
                    return 0, f"mon.{name} already exists", b""
                return -17, f"mon.{name} already exists", b""
            ops.append(("add", name, (str(addr[0]), int(addr[1]))))
            self.propose_pending()
            return 0, f"adding mon.{name} at {tuple(addr)}", b""
        if prefix == "mon remove":
            name = str(cmd.get("name", ""))
            ops = self._pending()
            roster = self._effective_roster()
            if name not in roster:
                return -2, f"mon.{name} does not exist", b""
            if len(roster) == 1:
                return -22, "cannot remove the last monitor", b""
            ops.append(("remove", name))
            self.propose_pending()
            return 0, f"removed mon.{name}", b""
        if prefix == "mon dump":
            mm = self.mon.monmap
            lines = [f"epoch {mm.epoch}"]
            for name in mm.ranks():
                lines.append(f"mon.{name} {mm.addr_of(name)}")
            return 0, "\n".join(lines), mm.encode()
        if prefix == "quorum_status":
            import json
            return 0, json.dumps({
                "quorum": self.mon.elector.quorum,
                "leader": self.mon.elector.leader,
                "epoch": self.mon.elector.epoch,
            }), b""
        return None
