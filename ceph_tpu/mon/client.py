"""MonClient: a daemon/client session to the monitor quorum.

The mon/MonClient.cc analog: pick a mon, subscribe to maps, relay
commands (blocking with timeout + failover to another mon), surface
OSDMap updates to the owner via a callback.
"""

from __future__ import annotations

import itertools
from ..utils import denc
import threading
from typing import Callable

from ..msg import Dispatcher, Message, Messenger
from ..osd.osdmap import OSDMap, OSDMapIncremental
from ..utils.dout import DoutLogger
from .messages import (MMonCommand, MMonCommandAck, MMonMap, MMonSubscribe,
                       MOSDBoot, MOSDFailure, MOSDMapMsg, MPGTemp)
from .monmap import MonMap


class MonClient(Dispatcher):
    def __init__(self, msgr: Messenger, monmap: MonMap):
        self.msgr = msgr
        self.monmap = monmap
        self.log = DoutLogger("monc", msgr.name)
        self.osdmap = OSDMap()
        self.on_osdmap: Callable[[OSDMap], None] | None = None
        # pool ids whose CREATION we observed arrive as an incremental
        # chained onto a map we already held — for these, and only
        # these, an empty pg copy is known to be the complete initial
        # state rather than a reboot-emptied husk of older data
        self.pool_births_witnessed: set[int] = set()
        self._tid = itertools.count(1)
        self._acks: dict[int, tuple] = {}
        self._ack_cv = threading.Condition()
        self._cur_mon: str | None = None
        # standing subscriptions, renewed periodically: the mon drops a
        # session's subs when its (lossy) push link to us resets, and a
        # stranded push is never resent — without renewal one dropped
        # frame freezes our map forever (MonClient::tick sub renewal,
        # mon/MonClient.cc: _renew_subs on sub interval)
        self._sub_what: dict[str, int] = {}
        self._sub_stop = threading.Event()
        self._sub_lock = threading.Lock()
        self._sub_thread: threading.Thread | None = None
        self._sub_timer = None
        msgr.add_dispatcher_head(self)

    # -- session -----------------------------------------------------------

    def _target(self) -> tuple[str, tuple]:
        if self._cur_mon not in self.monmap.mons:
            self._cur_mon = None          # roster changed under us
        name = self._cur_mon or self.monmap.ranks()[0]
        self._cur_mon = name
        return f"mon.{name}", self.monmap.addr_of(name)

    def _hunt(self) -> None:
        """Fail over to the next mon."""
        ranks = self.monmap.ranks()
        if self._cur_mon is None or self._cur_mon not in ranks:
            self._cur_mon = ranks[0]
        else:
            i = (ranks.index(self._cur_mon) + 1) % len(ranks)
            self._cur_mon = ranks[i]

    def subscribe(self, what: dict) -> None:
        self._sub_what.update(what)
        entity, addr = self._target()
        self.msgr.send_message(MMonSubscribe(what=what), entity, addr)
        with self._sub_lock:
            if self._sub_thread is not None or self._sub_timer is not None:
                return
            # periodic renewal rides the messenger's own loop (both
            # stacks expose call_later) — a session costs no renewal
            # thread; the thread remains only for bare test doubles
            if hasattr(self.msgr, "call_later"):
                self._sub_timer = self.msgr.call_later(
                    self._renew_interval(), self._renew_tick)
            else:
                self._sub_thread = threading.Thread(
                    target=self._renew_loop, daemon=True,
                    name=f"monc-renew-{self.msgr.name}")
                self._sub_thread.start()

    def sub_want_osdmap(self, start: int = 0) -> None:
        self.subscribe({"osdmap": start})

    def renew_subs(self) -> None:
        """Re-assert standing subscriptions from our CURRENT epochs.

        Idempotent at the mon: a start past its latest epoch sends
        nothing back (both osdmap and monmap subs are epoch-gated).
        Heals both a mon-side session drop (lossy push-link reset pops
        mon.subs) and a stranded push (the mon optimistically advanced
        our want past maps we never saw)."""
        what = {}
        if "osdmap" in self._sub_what:
            what["osdmap"] = self.osdmap.epoch + 1
        if "monmap" in self._sub_what:
            what["monmap"] = self.monmap.epoch + 1
        if not what:
            return
        try:
            entity, addr = self._target()
            self.msgr.send_message(MMonSubscribe(what=what), entity,
                                   addr)
        except RuntimeError:
            pass          # messenger shut down

    def _hunt_if_dead(self) -> None:
        """The session to the current mon rides a LOSSLESS link: a
        dead mon never produces a reset event, it just reconnect-loops
        forever with our sends stranded in its queue.  If the link has
        no live socket across TWO consecutive renew ticks (one tick
        could be an ordinary reconnect/handshake window), fail over
        (MonClient::tick hunting)."""
        if self.monmap.size < 2 or self._cur_mon is None:
            return
        conn = self.msgr.conns.get(f"mon.{self._cur_mon}")
        if conn is None or conn._writer is not None:
            self._dead_ticks = 0
            return
        self._dead_ticks = getattr(self, "_dead_ticks", 0) + 1
        if self._dead_ticks < 2:
            return
        self._dead_ticks = 0
        old = self._cur_mon
        self._hunt()
        if self._cur_mon != old:
            self.log.info("mon.%s unresponsive: hunting to mon.%s",
                          old, self._cur_mon)

    def _renew_interval(self) -> float:
        return float(getattr(self.msgr.conf,
                             "mon_sub_renew_interval", 2.0) or 2.0)

    def _renew_tick(self) -> None:
        """One renewal pass, on the messenger loop (non-blocking:
        sends are queued, never awaited)."""
        if self._sub_stop.is_set():
            return
        try:
            self._hunt_if_dead()
            self.renew_subs()
        finally:
            if not self._sub_stop.is_set():
                try:
                    self._sub_timer = self.msgr.call_later(
                        self._renew_interval(), self._renew_tick)
                except RuntimeError:
                    pass          # messenger shut down under us

    def _renew_loop(self) -> None:
        interval = self._renew_interval()
        while not self._sub_stop.wait(interval):
            self._hunt_if_dead()
            self.renew_subs()

    def shutdown(self) -> None:
        self._sub_stop.set()
        if self._sub_timer is not None:
            self._sub_timer.cancel()
            self._sub_timer = None
        self._auth_stop = True

    # -- commands ----------------------------------------------------------

    def command(self, cmd: dict, timeout: float = 30.0) -> tuple[int, str, bytes]:
        """Send an admin command; failover between mons until acked."""
        tid = next(self._tid)
        deadline = threading.TIMEOUT_MAX if timeout is None else timeout
        attempts = max(3, self.monmap.size + 1)
        per_try = max(2.0, deadline / attempts)
        for _ in range(attempts):
            entity, addr = self._target()
            self.msgr.send_message(MMonCommand(tid=tid, cmd=cmd),
                                   entity, addr)
            with self._ack_cv:
                ok = self._ack_cv.wait_for(lambda: tid in self._acks,
                                           per_try)
                if ok:
                    return self._acks.pop(tid)
            self._hunt()
        return -110, "command timed out", b""

    # -- cephx service tickets + rotating keys -----------------------------
    #
    # CephxProtocol's TGS flow, client side: fetch service tickets
    # over the (statically-authenticated, frame-signed) mon channel
    # and renew them at ~ttl/3; a service daemon additionally fetches
    # its own class's ROTATING secrets on the same cadence so its
    # messenger can redeem clients' tickets.  Both run on one
    # background thread — the messenger's connect coroutine only ever
    # reads the CACHE (a blocking fetch inside the event loop would
    # deadlock against the mon session riding the same messenger).

    def enable_service_auth(self, msgrs: list, own_service: str | None,
                            ticket_services: list[str],
                            clock=None) -> None:
        from ..utils import denc as _denc
        import base64
        self._tickets: dict[str, dict] = getattr(self, "_tickets", {})
        for m in msgrs:
            m.ticket_provider = self._tickets.get
            if clock is not None:
                m.ticket_clock = clock.now

        def refresh_once() -> float:
            ttl = None
            for svc in ticket_services:
                rv, _out, data = self.command(
                    {"prefix": "auth get-ticket", "service": svc},
                    timeout=10.0)
                if rv == 0 and data:
                    t = _denc.loads(data)
                    self._tickets[svc] = t
                    ttl = float(self.msgr.conf.auth_service_ticket_ttl)
            if own_service:
                rv, _out, data = self.command(
                    {"prefix": "auth get-rotating",
                     "service": own_service}, timeout=10.0)
                if rv == 0 and data:
                    rot = _denc.loads(data)
                    keys = {int(r["id"]): base64.b64decode(r["secret"])
                            for r in rot}
                    for m in msgrs:
                        m.rotating_keys = keys
            return ttl or float(self.msgr.conf.auth_service_ticket_ttl)

        def loop() -> None:
            import time as _time
            while not getattr(self, "_auth_stop", False):
                try:
                    ttl = refresh_once()
                except Exception:
                    ttl = 5.0
                # REAL-time cadence: ticket expiry stamps ride the
                # cluster clock, but renewal just needs to happen
                # often enough; ttl/3 in real seconds over-renews
                # under a ManualClock, never under-renews
                _time.sleep(max(0.5, ttl / 3.0))

        t = threading.Thread(target=loop, daemon=True,
                             name=f"cephx-renew-{self.msgr.name}")
        self._auth_thread = t
        t.start()

    # -- osd daemon helpers ------------------------------------------------

    def send(self, msg) -> None:
        """Send an arbitrary message to the current mon."""
        entity, addr = self._target()
        self.msgr.send_message(msg, entity, addr)

    def send_boot(self, osd_id: int, addr, hb_addr=None) -> None:
        entity, maddr = self._target()
        self.msgr.send_message(
            MOSDBoot(osd_id=osd_id, addr=tuple(addr),
                     heartbeat_addr=tuple(hb_addr) if hb_addr else None),
            entity, maddr)

    def report_failure(self, target_osd: int, failed_for: float) -> None:
        entity, addr = self._target()
        self.msgr.send_message(
            MOSDFailure(target_osd=target_osd, failed_for=failed_for),
            entity, addr)

    def cluster_log(self, level: str, text: str) -> None:
        """Send one cluster-log entry (LogClient -> LogMonitor)."""
        from .messages import MLogMsg
        entity, addr = self._target()
        self.msgr.send_message(
            MLogMsg(entries=[{"level": level, "text": text}]),
            entity, addr)

    def send_pg_stats(self, osd_id: int, stats: dict,
                      epoch: int, flags: dict | None = None) -> None:
        """Primary-pg stats for the mon's PGMap/health aggregation;
        `flags` carries per-daemon health markers (e.g. a device-
        degraded EC codec) the mon folds into its health report."""
        from .messages import MPGStats
        entity, addr = self._target()
        self.msgr.send_message(
            MPGStats(osd_id=osd_id, stats=stats, epoch=epoch,
                     flags=flags),
            entity, addr)

    def send_pg_temp(self, osd_id: int, pg_temp: dict) -> None:
        entity, addr = self._target()
        self.msgr.send_message(MPGTemp(osd_id=osd_id, pg_temp=pg_temp),
                               entity, addr)

    # -- dispatch ----------------------------------------------------------

    def ms_dispatch(self, conn, msg: Message) -> bool:
        if isinstance(msg, MMonCommandAck):
            with self._ack_cv:
                self._acks[msg.tid] = (msg.retval, msg.out, msg.data)
                self._ack_cv.notify_all()
            return True
        if isinstance(msg, MOSDMapMsg):
            self._handle_osdmap(msg)
            return True
        if isinstance(msg, MMonMap):
            self.monmap = MonMap.decode(msg.monmap)
            if self._cur_mon is not None and \
                    self._cur_mon not in self.monmap.mons:
                # our session mon was removed from the map: fail over
                # before the next _target()/_hunt() would KeyError
                self._cur_mon = self.monmap.ranks()[0] \
                    if self.monmap.mons else None
            return True
        return False

    def _handle_osdmap(self, msg: MOSDMapMsg) -> None:
        before = self.osdmap.epoch
        if msg.full is not None:
            full = OSDMap.decode(msg.full)
            if full.epoch >= self.osdmap.epoch:
                # pools first learned from a FULL map are of unknown
                # age (boot catch-up, gap refetch): we did NOT watch
                # them come to life — a consumer instantiating their
                # pgs fresh must assume data may already exist
                # elsewhere (see pool_birth_witnessed)
                self.pool_births_witnessed.difference_update(
                    set(full.pools) - set(self.osdmap.pools))
                self.osdmap = full
        for blob in msg.incrementals:
            inc = denc.loads(blob)
            if not isinstance(inc, OSDMapIncremental):
                raise denc.DencError("not an OSDMapIncremental")
            if inc.epoch == self.osdmap.epoch + 1:
                if before > 0:
                    # born in front of us: an empty pg of this pool IS
                    # the complete initial copy.  `before` guards the
                    # bootstrap replay — a want-from-epoch-1 request
                    # answers with the WHOLE incremental history
                    # chained from zero, and replaying an old pool's
                    # creation is not witnessing it
                    self.pool_births_witnessed.update(inc.new_pools)
                for pid in inc.removed_pools:
                    self.pool_births_witnessed.discard(pid)
                self.osdmap.apply_incremental(inc)
        if msg.epoch > self.osdmap.epoch:
            # gap: a previous push was lost (lossy mon link) and these
            # incrementals don't chain onto our map — re-request the
            # missing range instead of silently freezing (the reference
            # OSDMap subscribe-from-epoch catch-up)
            self.sub_want_osdmap(self.osdmap.epoch + 1)
        if self.on_osdmap and self.osdmap.epoch != before:
            try:
                self.on_osdmap(self.osdmap)
            except Exception:
                self.log.error("osdmap callback failed")

    def ms_handle_reset(self, conn) -> None:
        self._hunt()
