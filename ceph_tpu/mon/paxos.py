"""Paxos: the single replicated transaction log (mon/Paxos.{h,cc} analog).

One value sequence shared by all services.  Protocol phases exactly as
the reference:

  * recovery (leader only, after every election): OP_COLLECT with a
    fresh proposal number -> peons promise (if pn beats accepted_pn)
    and reply OP_LAST carrying last_committed plus any uncommitted
    (version, pn, value); the leader re-proposes the highest-pn
    uncommitted value at last_committed+1 and catches lagging peons up
    by shipping committed values inside OP_COLLECT/OP_LAST (share).
  * steady state: OP_BEGIN(version, value) -> peons journal the pending
    value, OP_ACCEPT -> when the WHOLE quorum accepted (Paxos.cc
    requires all quorum members, not a bare majority), the leader
    commits locally and broadcasts OP_COMMIT.
  * leases: after commit the leader issues OP_LEASE(last_committed,
    expiry) so peons may serve reads (Paxos.cc:623).

Values are MonitorDBStore transaction blobs; committing = applying the
blob to the store + bumping last_committed, all in one KV transaction.
"""

from __future__ import annotations

from ..utils import denc
from typing import Callable

from ..utils.dout import DoutLogger
from .messages import MMonPaxos
from .store import MonitorDBStore

COLLECT = "collect"
LAST = "last"
BEGIN = "begin"
ACCEPT = "accept"
COMMIT = "commit"
LEASE = "lease"
LEASE_ACK = "lease_ack"
SYNC = "sync"

SVC = "paxos"


class Paxos:
    def __init__(self, name: str, store: MonitorDBStore,
                 send: Callable[[str, MMonPaxos], None],
                 on_commit: Callable[[int], None],
                 lease_duration: float = 5.0, clock=None,
                 schedule: Callable | None = None,
                 on_stall: Callable | None = None,
                 phase_timeout: float = 10.0,
                 trim_max: int = 500, trim_keep: int = 250):
        from ..utils.clock import SystemClock
        self.clock = clock or SystemClock()
        # collect/accept phase watchdog: a lost LAST/ACCEPT (e.g. a
        # peon that died or demoted mid-round) must not wedge the
        # leader forever (Paxos::collect_timeout/accept_timeout ->
        # bootstrap in the reference)
        self.schedule = schedule
        self.on_stall = on_stall
        self.phase_timeout = phase_timeout
        self._phase_timer = None
        self.perf = None                 # optional PerfCounters
        # optional op-trace hook: tracer(event, version) fires at
        # "begin" (value enters the accept round) and "commit" (value
        # applied + visible) — the monitor turns these into
        # paxos.propose / paxos.commit spans on tracked command ops
        self.tracer: Callable | None = None
        self.name = name
        self.store = store
        self.send = send
        self.on_commit = on_commit       # on_commit(version) -> refresh
        # fired when the leader becomes writeable: the monitor drains
        # service proposals queued while we were recovering.  Without
        # this, a proposal queued mid-recovery waits for the NEXT
        # commit to flush it — and if no commit ever follows, it is
        # stranded forever (the mon-add-acked-but-never-committed
        # membership race)
        self.on_active: Callable | None = None
        self.lease_duration = lease_duration
        # trim: keep the committed window bounded (Paxos.cc trim);
        # peers behind the trim point rejoin via full store sync
        self.trim_max = trim_max
        self.trim_keep = trim_keep
        self.log = DoutLogger("paxos", name)

        self.leader: str | None = None
        self.quorum: list[str] = []
        self.rank = 0

        self.last_committed = store.get_int(SVC, "last_committed")
        self.first_committed = store.get_int(SVC, "first_committed")
        self.accepted_pn = store.get_int(SVC, "accepted_pn")

        # uncommitted (journaled but not committed) value
        self.uncommitted_v: int | None = None
        self.uncommitted_pn = 0
        self.uncommitted_value: bytes | None = None
        self._load_uncommitted()

        # leader collect state
        self.collecting = False
        self.collect_acks: set[str] = set()
        self.collect_max_last = 0
        self.best_uncommitted: tuple[int, int, bytes] | None = None
        self._peer_last: dict[str, int] = {}   # peer -> last_committed

        # leader begin state
        self.pending_value: bytes | None = None
        self.pending_v = 0
        self.accept_acks: set[str] = set()
        self.proposals: list[tuple[bytes, Callable | None]] = []
        self._pending_done: Callable | None = None

        # lease
        self.lease_expire = 0.0
        self.active = False              # writeable (leader, recovered)

    # -- persistence helpers ----------------------------------------------

    def _load_uncommitted(self) -> None:
        blob = self.store.get(SVC, "uncommitted")
        if blob:
            v, pn, value = denc.loads(blob)
            if v > self.last_committed:
                self.uncommitted_v, self.uncommitted_pn = v, pn
                self.uncommitted_value = value

    def _save_uncommitted(self, txn, v: int | None, pn: int = 0,
                          value: bytes | None = None) -> None:
        if v is None:
            txn.rmkey(SVC, "uncommitted")
        else:
            txn.set(SVC, "uncommitted", denc.dumps((v, pn, value)))

    def new_pn(self) -> int:
        """Fresh proposal number: counter*100 + rank (Paxos get_new_pn)."""
        cur = max(self.accepted_pn, self.store.get_int(SVC, "max_pn"))
        pn = (cur // 100 + 1) * 100 + self.rank
        txn = self.store.transaction()
        self.store.put_int(txn, SVC, "max_pn", pn)
        self.store.apply_transaction(txn)
        return pn

    # -- role changes ------------------------------------------------------

    def leader_init(self, quorum: list[str], rank: int) -> None:
        self.leader = self.name
        self.quorum = quorum
        self.rank = rank
        self.active = False
        self.pending_value = None
        if len(quorum) == 1:
            # singleton: no peons to collect from
            self.accepted_pn = self.new_pn()
            self._commit_uncommitted_if_any()
            self._activate()
            return
        self.collecting = True
        self.collect_acks = {self.name}
        self.collect_max_last = self.last_committed
        self._peer_last = {}
        self.best_uncommitted = (
            (self.uncommitted_v, self.uncommitted_pn, self.uncommitted_value)
            if self.uncommitted_v else None)
        pn = self.new_pn()
        self.accepted_pn = pn
        txn = self.store.transaction()
        self.store.put_int(txn, SVC, "accepted_pn", pn)
        self.store.apply_transaction(txn)
        if self.perf:
            self.perf.inc("collect")
        for peer in quorum:
            if peer != self.name:
                self.send(peer, MMonPaxos(
                    op=COLLECT, pn=pn, last_committed=self.last_committed,
                    first_committed=self.first_committed))
        self._arm_phase_timer(lambda: self.collecting, "collect")

    def peon_init(self, leader: str, quorum: list[str], rank: int) -> None:
        self.leader = leader
        self.quorum = quorum
        self.rank = rank
        self.active = False
        self.collecting = False
        self.pending_value = None
        # grace for the new leader's first LEASE: the monitor's
        # lease-timeout watchdog must not re-trip on the PREVIOUS
        # leader's stale expiry the instant we lose an election
        self.lease_expire = self.clock.now() + self.lease_duration
        self._cancel_phase_timer()

    # -- phase watchdog -----------------------------------------------------

    def _arm_phase_timer(self, still_stuck: Callable[[], bool],
                         phase: str) -> None:
        self._cancel_phase_timer()
        if self.schedule is None or self.on_stall is None:
            return

        def check():
            self._phase_timer = None
            if self.is_leader() and still_stuck():
                self.log.warn("%s phase stalled for %.0fs, bootstrapping",
                              phase, self.phase_timeout)
                self.on_stall()

        self._phase_timer = self.schedule(self.phase_timeout, check)

    def _cancel_phase_timer(self) -> None:
        if self._phase_timer is not None:
            try:
                self._phase_timer.cancel()
            except Exception:
                pass
            self._phase_timer = None

    # -- recovery phase ----------------------------------------------------

    def handle(self, msg: MMonPaxos) -> None:
        op = msg.op
        if op == COLLECT:
            self._handle_collect(msg)
        elif op == LAST:
            self._handle_last(msg)
        elif op == BEGIN:
            self._handle_begin(msg)
        elif op == ACCEPT:
            self._handle_accept(msg)
        elif op == COMMIT:
            self._handle_commit(msg)
        elif op == LEASE:
            self._handle_lease(msg)
        elif op == LEASE_ACK:
            pass
        elif op == SYNC:
            self._handle_sync(msg)

    def _committed_range(self, first: int, last: int) -> dict[int, bytes]:
        out = {}
        for v in range(first, last + 1):
            blob = self.store.get_version(SVC, v)
            if blob is not None:
                out[v] = blob
        return out

    def _handle_collect(self, msg: MMonPaxos) -> None:
        if msg.pn < self.accepted_pn:
            return   # promised a higher pn already; ignore (leader times out)
        self.accepted_pn = msg.pn
        txn = self.store.transaction()
        self.store.put_int(txn, SVC, "accepted_pn", msg.pn)
        self.store.apply_transaction(txn)
        # share commits the leader is missing; a leader behind OUR
        # trim point cannot replay version-by-version — ship the whole
        # store instead (Monitor sync_start semantics)
        commits = {}
        sync = None
        if msg.last_committed < self.last_committed:
            if msg.last_committed + 1 < self.first_committed:
                self.log.info("leader %s at v%d behind our trim point "
                              "v%d: full sync", msg.src,
                              msg.last_committed, self.first_committed)
                sync = self.store.dump_all()
            else:
                commits = self._committed_range(msg.last_committed + 1,
                                                self.last_committed)
        reply = MMonPaxos(op=LAST, pn=msg.pn,
                          last_committed=self.last_committed,
                          first_committed=self.first_committed,
                          commits=commits, sync=sync,
                          uncommitted=(self.uncommitted_v,
                                       self.uncommitted_pn,
                                       self.uncommitted_value)
                          if self.uncommitted_v else None)
        self.send(msg.src, reply)

    def _handle_last(self, msg: MMonPaxos) -> None:
        if not self.collecting or msg.pn != self.accepted_pn:
            return
        sync = getattr(msg, "sync", None)
        if sync:
            # we (the new leader) are behind the quorum's trim point:
            # adopt the peon's whole store, keep our proposal number
            self._absorb_sync(sync)
            txn = self.store.transaction()
            self.store.put_int(txn, SVC, "accepted_pn", self.accepted_pn)
            self.store.apply_transaction(txn)
        # absorb shared commits
        for v, blob in sorted(getattr(msg, "commits", {}).items()):
            if v == self.last_committed + 1:
                self._apply_commit(v, blob)
        self._peer_last[msg.src] = msg.last_committed
        if msg.last_committed > self.collect_max_last:
            self.collect_max_last = msg.last_committed
        unc = getattr(msg, "uncommitted", None)
        if unc and unc[0] is not None:
            if (self.best_uncommitted is None
                    or unc[1] > self.best_uncommitted[1]):
                self.best_uncommitted = tuple(unc)
        self.collect_acks.add(msg.src)
        if self.collect_acks >= set(self.quorum):
            self.collecting = False
            self._cancel_phase_timer()
            self._post_collect()

    def _post_collect(self) -> None:
        # catch up lagging peons by sharing commits in BEGIN-free path:
        # peons learn via commit messages; one behind the trim point
        # gets the whole store instead (its missing versions are gone)
        for peer in self.quorum:
            if peer == self.name:
                continue
            plast = self._peer_last.get(peer, 0)
            if plast + 1 < self.first_committed:
                self.log.info("peon %s at v%d behind trim point v%d: "
                              "full sync", peer, plast,
                              self.first_committed)
                self.send(peer, MMonPaxos(
                    op=SYNC, sync=self.store.dump_all(),
                    last_committed=self.last_committed,
                    first_committed=self.first_committed))
                continue
            self.send(peer, MMonPaxos(
                op=COMMIT, last_committed=self.last_committed,
                commits=self._committed_range(
                    max(self.first_committed, plast + 1),
                    self.last_committed)))
        if (self.best_uncommitted
                and self.best_uncommitted[0] == self.last_committed + 1):
            v, pn, value = self.best_uncommitted
            self.log.info("re-proposing uncommitted v%d", v)
            self.best_uncommitted = None
            self._begin(value, None)
            return
        self.best_uncommitted = None
        self._commit_uncommitted_if_any()
        self._activate()

    def _commit_uncommitted_if_any(self) -> None:
        if (self.uncommitted_v
                and self.uncommitted_v == self.last_committed + 1
                and len(self.quorum) == 1):
            # singleton recovery: our own journaled value wins
            self._apply_commit(self.uncommitted_v, self.uncommitted_value)
        self.uncommitted_v = None
        self.uncommitted_value = None

    def _activate(self) -> None:
        self.active = True
        self._extend_lease()
        self.log.info("active as leader at v%d", self.last_committed)
        if self.on_active is not None:
            try:
                self.on_active()
            except Exception:
                self.log.error("on_active callback failed")
        self._propose_queued()

    # -- steady state ------------------------------------------------------

    def propose(self, value: bytes, done: Callable | None = None) -> None:
        """Queue a txn blob for commit (leader only)."""
        assert self.is_leader()
        self.proposals.append((value, done))
        self._propose_queued()

    def is_leader(self) -> bool:
        return self.leader == self.name

    def is_writeable(self) -> bool:
        return self.is_leader() and self.active

    def is_readable(self) -> bool:
        if self.is_leader():
            return self.active
        return self.clock.now() < self.lease_expire

    def _propose_queued(self) -> None:
        if (not self.active or self.pending_value is not None
                or not self.proposals):
            return
        value, done = self.proposals.pop(0)
        self._pending_done = done
        self._begin(value, done)

    def _begin(self, value: bytes, done: Callable | None) -> None:
        if self.perf:
            self.perf.inc("begin")
        if self.tracer:
            try:
                self.tracer("begin", self.last_committed + 1)
            except Exception:
                pass             # tracing must never wedge consensus
        self.pending_v = self.last_committed + 1
        self.pending_value = value
        self._pending_done = done
        self.accept_acks = {self.name}
        # journal our own uncommitted value
        txn = self.store.transaction()
        self._save_uncommitted(txn, self.pending_v, self.accepted_pn, value)
        self.store.apply_transaction(txn)
        self.uncommitted_v = self.pending_v
        self.uncommitted_pn = self.accepted_pn
        self.uncommitted_value = value
        if len(self.quorum) == 1:
            self._commit_pending()
            return
        for peer in self.quorum:
            if peer != self.name:
                self.send(peer, MMonPaxos(
                    op=BEGIN, pn=self.accepted_pn, version=self.pending_v,
                    value=value, last_committed=self.last_committed))
        self._arm_phase_timer(
            lambda: self.pending_value is not None, "accept")

    def _handle_begin(self, msg: MMonPaxos) -> None:
        if msg.pn < self.accepted_pn:
            return
        self.accepted_pn = msg.pn
        txn = self.store.transaction()
        self.store.put_int(txn, SVC, "accepted_pn", msg.pn)
        self._save_uncommitted(txn, msg.version, msg.pn, msg.value)
        self.store.apply_transaction(txn)
        self.uncommitted_v = msg.version
        self.uncommitted_pn = msg.pn
        self.uncommitted_value = msg.value
        # crash site: the value is journaled (accepted) but the ACCEPT
        # never leaves — the PAR invariant requires a remount to still
        # OFFER this value during the next leader's collect phase
        self.store.maybe_crash("paxos.post_accept_pre_ack")
        self.send(msg.src, MMonPaxos(op=ACCEPT, pn=msg.pn,
                                     version=msg.version))

    def _handle_accept(self, msg: MMonPaxos) -> None:
        if (self.pending_value is None or msg.pn != self.accepted_pn
                or msg.version != self.pending_v):
            return
        self.accept_acks.add(msg.src)
        if self.accept_acks >= set(self.quorum):
            self._commit_pending()

    def _commit_pending(self) -> None:
        v, value = self.pending_v, self.pending_value
        done = self._pending_done
        self.pending_value = None
        self._pending_done = None
        self._cancel_phase_timer()
        # trace BEFORE applying: _apply_commit runs the monitor's
        # on_commit refresh (which drains client acks), and the
        # paxos.commit span must already be open to cover it
        if self.tracer:
            try:
                self.tracer("commit", v)
            except Exception:
                pass
        self._apply_commit(v, value)
        for peer in self.quorum:
            if peer != self.name:
                self.send(peer, MMonPaxos(
                    op=COMMIT, last_committed=self.last_committed,
                    commits={v: value}))
        self._extend_lease()
        if done:
            try:
                done()
            except Exception:
                self.log.error("proposal completion callback failed")
        if not self.active and self.is_leader():
            # the value just committed was the recovery round's
            # re-proposed uncommitted value (_post_collect returned
            # before activating): the round is now complete
            self._activate()
        else:
            self._propose_queued()

    def _apply_commit(self, v: int, value: bytes) -> None:
        """Apply the txn blob + bump last_committed atomically."""
        assert v == self.last_committed + 1, (v, self.last_committed)
        # crash site: nothing of the commit reached disk yet — the
        # journaled uncommitted value must survive the remount
        self.store.maybe_crash("paxos.pre_commit")
        txn = self.store.transaction()
        for op in denc.loads(value):
            txn.ops.append(op)
        self.store.put_version(txn, SVC, v, value)
        self.store.put_int(txn, SVC, "last_committed", v)
        if self.first_committed == 0:
            self.first_committed = 1
            self.store.put_int(txn, SVC, "first_committed", 1)
        # the seal vouches for the whole commit; it precedes the
        # uncommitted-record removal so ANY prefix tear keeps the
        # accepted value on disk (a mon never forgets what it
        # accepted — the PAR invariant)
        self.store.seal_commit(txn, v, value)
        self._save_uncommitted(txn, None)
        # crash site: the commit transaction tears — a seeded prefix
        # (or reordered subset) of its ops land; check_integrity
        # detects the damage at remount and the quorum repairs it
        self.store.apply_transaction(txn, torn_site="paxos.mid_commit")
        self.last_committed = v
        # a trim blob moves first_committed inside the applied txn
        self.first_committed = max(
            self.first_committed,
            self.store.get_int(SVC, "first_committed"))
        self.uncommitted_v = None
        self.uncommitted_value = None
        if self.perf:
            self.perf.inc("commit")
        self.on_commit(v)

    def _handle_commit(self, msg: MMonPaxos) -> None:
        for v, blob in sorted(getattr(msg, "commits", {}).items()):
            if v == self.last_committed + 1:
                self._apply_commit(v, blob)
        # peon lease is implied refreshed by commit traffic
        self.lease_expire = self.clock.now() + self.lease_duration

    # -- trim + full store sync --------------------------------------------

    def _absorb_sync(self, entries: list) -> None:
        self.store.restore_all(entries)
        txn = self.store.transaction()
        txn.rmkey(SVC, "uncommitted")     # the donor's, not ours
        self.store.apply_transaction(txn)
        self.last_committed = self.store.get_int(SVC, "last_committed")
        self.first_committed = self.store.get_int(SVC, "first_committed")
        self.uncommitted_v = None
        self.uncommitted_value = None
        self.log.info("store sync absorbed: now at v%d (first v%d)",
                      self.last_committed, self.first_committed)
        self.on_commit(self.last_committed)

    def _handle_sync(self, msg: MMonPaxos) -> None:
        """Peon: the quorum trimmed past our last_committed — replace
        our store wholesale and resume from the leader's head."""
        self._absorb_sync(msg.sync)
        self.lease_expire = self.clock.now() + self.lease_duration

    def maybe_trim(self) -> None:
        """Leader: propose erasing committed versions older than the
        keep window (Paxos::trim) — the erase rides the log itself, so
        every quorum member trims identically."""
        if not self.is_writeable():
            return
        if self.last_committed - self.first_committed < self.trim_max:
            return
        target = self.last_committed - self.trim_keep
        if target <= self.first_committed:
            return
        self.log.info("trimming paxos v%d..v%d", self.first_committed,
                      target)
        txn = self.store.transaction()
        self.store.erase_version_range(txn, SVC, self.first_committed,
                                       target)
        self.store.put_int(txn, SVC, "first_committed", target)
        self.propose(denc.dumps(txn.ops))

    # -- leases ------------------------------------------------------------

    def _extend_lease(self) -> None:
        if self.perf:
            self.perf.inc("lease")
        self.lease_expire = self.clock.now() + self.lease_duration
        for peer in self.quorum:
            if peer != self.name:
                self.send(peer, MMonPaxos(
                    op=LEASE, last_committed=self.last_committed,
                    lease_expire=self.lease_expire))

    def _handle_lease(self, msg: MMonPaxos) -> None:
        self.lease_expire = self.clock.now() + self.lease_duration
        self.active = True
        self.send(msg.src, MMonPaxos(op=LEASE_ACK))

    def tick(self) -> None:
        """Leader: renew leases periodically."""
        if self.is_leader() and self.active:
            self._extend_lease()
