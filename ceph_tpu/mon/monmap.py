"""MonMap: the monitor roster (mon/MonMap.h analog).

Rank = index in sorted name order; elections prefer the lowest rank.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..utils import denc
from ..utils.denc import denc_type


@denc_type
@dataclass
class MonMap:
    epoch: int = 1
    fsid: str = ""
    mons: dict[str, tuple] = field(default_factory=dict)   # name -> addr

    def add(self, name: str, addr: tuple) -> None:
        self.mons[name] = tuple(addr)

    def remove(self, name: str) -> None:
        self.mons.pop(name, None)

    def copy(self) -> "MonMap":
        return MonMap(epoch=self.epoch, fsid=self.fsid,
                      mons=dict(self.mons))

    @property
    def size(self) -> int:
        return len(self.mons)

    def ranks(self) -> list[str]:
        return sorted(self.mons)

    def rank_of(self, name: str) -> int:
        return self.ranks().index(name)

    def name_of_rank(self, rank: int) -> str:
        return self.ranks()[rank]

    def addr_of(self, name: str) -> tuple:
        return self.mons[name]

    def quorum_needed(self) -> int:
        return self.size // 2 + 1

    def encode(self) -> bytes:
        return denc.dumps(self)

    @staticmethod
    def decode(b: bytes) -> "MonMap":
        m = denc.loads(b)
        if not isinstance(m, MonMap):
            raise denc.DencError("not a MonMap")
        return m
