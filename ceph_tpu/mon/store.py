"""MonitorDBStore: versioned service state over a KV backend.

The mon/MonitorDBStore.h analog: every PaxosService keeps
(service, version) -> blob entries plus scalar markers
(first_committed, last_committed, latest full snapshots), all written
through atomic KV transactions so a commit is all-or-nothing.
"""

from __future__ import annotations

from ..kv import KeyValueDB, KVTransaction, MemDB, SqliteDB


def _vkey(version: int) -> str:
    return f"{version:020d}"


class MonitorDBStore:
    def __init__(self, path: str = ""):
        self.db: KeyValueDB = SqliteDB(path) if path else MemDB()

    def open(self) -> None:
        self.db.open()

    def close(self) -> None:
        self.db.close()

    def transaction(self) -> KVTransaction:
        return self.db.transaction()

    def apply_transaction(self, txn: KVTransaction) -> None:
        self.db.submit_transaction(txn, sync=True)

    # -- typed helpers -----------------------------------------------------

    def put(self, txn: KVTransaction, service: str, key: str,
            value: bytes) -> None:
        txn.set(service, key, value)

    def put_version(self, txn: KVTransaction, service: str, version: int,
                    value: bytes) -> None:
        txn.set(service, _vkey(version), value)

    def get(self, service: str, key: str) -> bytes | None:
        return self.db.get(service, key)

    def get_version(self, service: str, version: int) -> bytes | None:
        return self.db.get(service, _vkey(version))

    def get_int(self, service: str, key: str, default: int = 0) -> int:
        v = self.db.get(service, key)
        return int(v.decode()) if v is not None else default

    def put_int(self, txn: KVTransaction, service: str, key: str,
                value: int) -> None:
        txn.set(service, key, str(value).encode())

    def erase_version_range(self, txn: KVTransaction, service: str,
                            first: int, last: int) -> None:
        for v in range(first, last):
            txn.rmkey(service, _vkey(v))

    # -- full store sync (Monitor::sync_* analog) --------------------------

    def dump_all(self) -> list[tuple[str, str, bytes]]:
        """Every (service, key, value) — the payload a mon behind the
        paxos trim point needs to rejoin."""
        out = []
        for prefix in self.db.prefixes():
            for key, value in self.db.iterate(prefix):
                out.append((prefix, key, value))
        return out

    def restore_all(self, entries: list) -> None:
        """Replace the whole store with `entries` atomically."""
        txn = self.transaction()
        for prefix in self.db.prefixes():
            txn.rmkeys_by_prefix(prefix)
        for prefix, key, value in entries:
            txn.set(prefix, key, value)
        self.apply_transaction(txn)
