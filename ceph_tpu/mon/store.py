"""MonitorDBStore: versioned service state over a KV backend.

The mon/MonitorDBStore.h analog: every PaxosService keeps
(service, version) -> blob entries plus scalar markers
(first_committed, last_committed, latest full snapshots), all written
through atomic KV transactions so a commit is all-or-nothing.

Crash plane (Protocol-Aware Recovery, Alagappan et al., FAST '18):
the paxos commit path threads named crash points through this store
(`paxos.pre_commit`, `paxos.mid_commit`, `paxos.post_accept_pre_ack`),
and `paxos.mid_commit` applies the ALICE torn-write model to the
commit transaction itself — a seeded prefix (or, with an fsync_reorder
rule armed, a seeded subset) of its ops land.  Every commit seals
itself with a `commit_seal` record written as the LAST op of the
commit transaction: (version, crc32c(value)).  At mount,
`check_integrity` compares the seal against the claimed
`last_committed` and the stored value blob — a torn commit is
DETECTED (seal missing/behind/ahead, or blob missing/crc-failing) and
the store rolls its claim back to the sealed floor so the quorum
repairs it by re-sharing commits, rather than the mon silently
adopting (or serving) a half-applied transaction.
"""

from __future__ import annotations

from typing import Callable

from ..kv import KeyValueDB, KVTransaction, MemDB, SqliteDB
from ..ops.crc32c import crc32c
from ..utils import denc
from ..utils.dout import DoutLogger
from ..utils.faults import CrashPoint

SVC = "paxos"


def _vkey(version: int) -> str:
    return f"{version:020d}"


class MonitorDBStore:
    def __init__(self, path: str = ""):
        self.db: KeyValueDB = SqliteDB(path) if path else MemDB()
        # crash plane: mirrors ObjectStore's — a fired crash point
        # freezes the store (nothing later reaches disk) and aborts
        # the owning monitor without acking
        self.owner = ""
        self.frozen = False
        self.crash_site = ""
        self.crash_callback: Callable | None = None
        self.log = DoutLogger("monstore", path or "mem")
        self.counters = {
            "paxos_torn_commit_repairs": 0,
            "fsync_reorder_windows": 0,
        }

    def open(self) -> None:
        self.db.open()

    def close(self) -> None:
        self.db.close()

    def transaction(self) -> KVTransaction:
        return self.db.transaction()

    # -- crash plane -------------------------------------------------------

    def freeze(self) -> None:
        self.frozen = True

    def _check_frozen(self) -> None:
        if self.frozen:
            raise CrashPoint(
                f"{self.owner or '?'}: mon store frozen (crashed"
                f"{' at ' + self.crash_site if self.crash_site else ''})")

    def _panic(self, site: str) -> None:
        self.frozen = True
        self.crash_site = site
        cb = self.crash_callback
        if cb is not None:
            try:
                cb(site)
            except Exception:
                pass
        raise CrashPoint(f"{self.owner or '?'} crashed at {site}")

    def maybe_crash(self, site: str) -> None:
        from ..utils import faults
        if faults.get().should_crash(self.owner, site):
            self._panic(site)

    def apply_transaction(self, txn: KVTransaction,
                          torn_site: str | None = None) -> None:
        """Submit atomically; when `torn_site` names an armed crash
        point, the transaction TEARS instead: a seeded prefix (or
        reordered subset) of its ops land and the store dies — the
        window `check_integrity` must detect at the next mount."""
        self._check_frozen()
        if torn_site is not None:
            from ..utils import faults
            fs = faults.get()
            if fs.should_crash(self.owner, torn_site):
                ops, reordered = fs.torn_ops(self.owner, txn.ops)
                if reordered:
                    self.counters["fsync_reorder_windows"] += 1
                part = self.db.transaction()
                part.ops = ops
                self.db.submit_transaction(part, sync=True)
                self._panic(torn_site)
        self.db.submit_transaction(txn, sync=True)

    # -- commit seal + torn-commit detection -------------------------------

    def seal_commit(self, txn: KVTransaction, version: int,
                    value: bytes) -> None:
        """Append the commit seal as the transaction's LAST op: any
        prefix tear lacks it, any subset tear mismatches it."""
        txn.set(SVC, "commit_seal",
                denc.dumps((int(version), crc32c(0, bytes(value)))))

    def check_integrity(self) -> int:
        """Detect (and locally contain) a torn paxos commit: verify
        the seal matches `last_committed` and that the claimed head
        version's value blob is present and crc-clean.  On damage,
        roll `last_committed` back to the last version that passes
        verification — the partial ops the torn transaction did land
        stay in place and are overwritten verbatim when the quorum
        re-shares the commits (every paxos value is an idempotent op
        list).  Returns the number of versions rolled back."""
        last = self.get_int(SVC, "last_committed")
        if last == 0:
            return 0
        seal = self.get(SVC, "commit_seal")
        seal_v, seal_crc = (denc.loads(seal) if seal is not None
                            else (None, None))
        first = max(1, self.get_int(SVC, "first_committed", 1))

        def version_ok(v: int) -> bool:
            blob = self.get_version(SVC, v)
            if blob is None:
                return False
            if seal_v == v and crc32c(0, bytes(blob)) != seal_crc:
                return False
            return True

        if seal_v == last and version_ok(last):
            # seal and head blob verify — but a reordered subset tear
            # can land the seal while dropping SERVICE ops of the same
            # transaction.  Every paxos value is an idempotent KV op
            # list, so re-applying the head version's blob heals that
            # window unconditionally (no-op on a clean store).
            self._reapply_version(last)
            return 0
        # torn: walk back to a verifiable floor (the seal's version if
        # its blob checks out, else the newest version whose blob is
        # present — versions below first_committed are trimmed, never
        # reachable)
        floor = last
        while floor >= first and not (version_ok(floor) and
                                      (seal_v is None or
                                       floor <= (seal_v or 0))):
            floor -= 1
        if floor < first:
            floor = 0 if first <= 1 else first - 1
        rolled = last - floor
        self.counters["paxos_torn_commit_repairs"] += 1
        self.log.warn(
            "torn paxos commit detected (claimed v%d, seal %s): "
            "rolling back to v%d for quorum repair", last,
            seal_v if seal is not None else "absent", floor)
        txn = self.transaction()
        self.put_int(txn, SVC, "last_committed", floor)
        self.db.submit_transaction(txn, sync=True)
        if floor >= first:
            # restore the floor version's full effects (idempotent op
            # list) so the local state is exactly "commit `floor` just
            # applied cleanly"; the quorum re-shares floor+1.. onward
            self._reapply_version(floor)
        return rolled

    def _reapply_version(self, v: int) -> None:
        """Restore version v's full effects.  Only ops whose target
        keys currently DIFFER are submitted, so a clean mount is
        write-free — the synced rewrite happens exactly when there is
        damage to heal."""
        blob = self.get_version(SVC, v)
        if blob is None:
            return
        txn = self.transaction()
        for op in denc.loads(blob):
            kind, prefix, key = op[0], op[1], op[2]
            cur = self.db.get(prefix, key)
            if kind == "set" and cur == op[3]:
                continue
            if kind == "rm" and cur is None:
                continue
            txn.ops.append(op)
        if txn.ops:
            self.seal_commit(txn, v, blob)
            self.db.submit_transaction(txn, sync=True)

    # -- typed helpers -----------------------------------------------------

    def put(self, txn: KVTransaction, service: str, key: str,
            value: bytes) -> None:
        txn.set(service, key, value)

    def put_version(self, txn: KVTransaction, service: str, version: int,
                    value: bytes) -> None:
        txn.set(service, _vkey(version), value)

    def get(self, service: str, key: str) -> bytes | None:
        return self.db.get(service, key)

    def get_version(self, service: str, version: int) -> bytes | None:
        return self.db.get(service, _vkey(version))

    def get_int(self, service: str, key: str, default: int = 0) -> int:
        v = self.db.get(service, key)
        return int(v.decode()) if v is not None else default

    def put_int(self, txn: KVTransaction, service: str, key: str,
                value: int) -> None:
        txn.set(service, key, str(value).encode())

    def erase_version_range(self, txn: KVTransaction, service: str,
                            first: int, last: int) -> None:
        for v in range(first, last):
            txn.rmkey(service, _vkey(v))

    # -- full store sync (Monitor::sync_* analog) --------------------------

    def dump_all(self) -> list[tuple[str, str, bytes]]:
        """Every (service, key, value) — the payload a mon behind the
        paxos trim point needs to rejoin."""
        out = []
        for prefix in self.db.prefixes():
            for key, value in self.db.iterate(prefix):
                out.append((prefix, key, value))
        return out

    def restore_all(self, entries: list) -> None:
        """Replace the whole store with `entries` atomically."""
        txn = self.transaction()
        for prefix in self.db.prefixes():
            txn.rmkeys_by_prefix(prefix)
        for prefix, key, value in entries:
            txn.set(prefix, key, value)
        self.apply_transaction(txn)
