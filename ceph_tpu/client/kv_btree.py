"""KvFlatBtree: a distributed, concurrent-client-safe B-tree over RADOS.

The key_value_store/kv_flat_btree_async.{h,cc} analog: keys live in
LEAF objects' omaps; one INDEX object's omap maps each leaf's key-range
upper bound to the leaf and carries "prefix" markers for in-flight
structural ops.  Order `k` follows the reference's thresholds
(kv_flat_btree_async.h:573, .cc:585 rebalance): a leaf with >= 2k
entries splits; a leaf dropping below k entries merges with a neighbor
(or the pair redistributes evenly when the merged load would itself
split).

Concurrency model (the reference's assert-version scheme, redesigned on
cls guards):
  * every leaf mutation is an in-OSD `put_guarded`/`rm_guarded` cls
    call that checks the leaf's version cell — a structural op bumps
    the version AND sets a dead marker, so a racing writer's guard
    fails and it re-walks the index;
  * index transitions (the commit point of a split/merge) are one
    atomic `update_index` cls call that checks the expected pre-image
    of every touched index entry — two racing splitters cannot both
    commit;
  * before committing, the structural op records a PREFIX marker in
    the index entry (timestamped, with the planned new state); a
    client that finds a stale marker heals it — roll FORWARD when the
    new leaves are all in place, roll BACK otherwise — so a client
    killed mid-split never wedges the tree.
"""

from __future__ import annotations

import time
import uuid

from ..utils import denc
from .rados import RadosError

INF = "\x7f~inf"                 # index bound sorting after any user key
VER_KEY = "\x00ver"              # leaf meta: version cell (bytes of int)
DEAD_KEY = "\x00dead"            # leaf meta: structural op killed it
PREFIX_TIMEOUT = 2.0             # seconds before a marker is "stale"


def _bound_key(user_key: str) -> str:
    return "k" + user_key


class KvFlatBtree:
    def __init__(self, ioctx, name: str, k: int = 2,
                 prefix_timeout: float = PREFIX_TIMEOUT):
        if k < 2:
            raise ValueError("order k must be >= 2")
        self.io = ioctx
        self.name = name
        self.k = k
        self.prefix_timeout = prefix_timeout
        self.index_oid = f"{name}.kvb.index"
        self._ensure_root()

    # -- layout helpers ----------------------------------------------------

    def _leaf_oid(self) -> str:
        return f"{self.name}.kvb.leaf.{uuid.uuid4().hex[:12]}"

    def _read_index(self) -> dict[str, dict]:
        try:
            raw = self.io.get_omap(self.index_oid)
        except RadosError:
            return {}
        return {k: denc.loads(v) for k, v in raw.items()}

    def _ensure_root(self) -> None:
        if self._read_index():
            return
        leaf = self._leaf_oid()
        try:
            self.io.execute(self.index_oid, "kvstore", "update_index",
                            denc.dumps({
                                "expect": {INF: None},
                                "set": {INF: denc.dumps(
                                    {"oid": leaf, "ver": 1})},
                            }))
            self.io.execute(leaf, "kvstore", "put_guarded", denc.dumps(
                {"kv": {VER_KEY: b"1"}, "guard": {}}))
        except RadosError as e:
            if e.errno != 125:            # lost the race: root exists
                raise

    def _find_entry(self, key: str) -> tuple[str, dict]:
        """(bound, entry) of the leaf covering `key`; heals stale
        prefix markers it trips over."""
        bk = _bound_key(key)
        while True:
            idx = self._read_index()
            if not idx:
                self._ensure_root()
                continue
            bound = min((b for b in idx if b >= bk or b == INF),
                        key=lambda b: (b == INF, b))
            entry = idx[bound]
            pfx = entry.get("prefix")
            if pfx is None:
                return bound, entry
            if time.time() - pfx["ts"] > self.prefix_timeout:
                self._heal(bound, entry)
            else:
                time.sleep(0.05)          # in-flight op: let it land

    # -- leaf I/O ----------------------------------------------------------

    def _leaf_items(self, oid: str) -> dict[str, bytes] | None:
        try:
            raw = self.io.get_omap(oid)
        except RadosError:
            return None
        if DEAD_KEY in raw:
            return None
        return raw

    @staticmethod
    def _user_items(raw: dict) -> dict[str, bytes]:
        return {k: v for k, v in raw.items() if not k.startswith("\x00")}

    # -- public API --------------------------------------------------------

    def set(self, key: str, value: bytes) -> None:
        if key.startswith("\x00") or _bound_key(key) >= INF:
            raise ValueError(f"invalid key {key!r}")
        while True:
            bound, entry = self._find_entry(key)
            try:
                out = self.io.execute(
                    entry["oid"], "kvstore", "put_guarded",
                    denc.dumps({
                        "kv": {key: bytes(value)},
                        "guard": {VER_KEY: str(entry["ver"]).encode(),
                                  DEAD_KEY: None},
                    }))
            except RadosError as e:
                if e.errno in (125, 2):   # split/merged under us
                    continue
                raise
            size = denc.loads(out)        # meta cells already excluded
            if size >= 2 * self.k:
                self._split(bound, entry)
            return

    def get(self, key: str) -> bytes:
        while True:
            _bound, entry = self._find_entry(key)
            raw = self._leaf_items(entry["oid"])
            if raw is None:
                continue                  # structural op won; re-walk
            if key not in raw:
                raise KeyError(key)
            return raw[key]

    def remove(self, key: str) -> None:
        while True:
            bound, entry = self._find_entry(key)
            try:
                out = self.io.execute(
                    entry["oid"], "kvstore", "rm_guarded",
                    denc.dumps({
                        "keys": [key],
                        "guard": {VER_KEY: str(entry["ver"]).encode(),
                                  DEAD_KEY: None},
                    }))
            except RadosError as e:
                if e.errno == 125:
                    continue
                if e.errno == 2:
                    # leaf vanished (merge) OR key truly absent
                    raw = self._leaf_items(entry["oid"])
                    if raw is None:
                        continue
                    raise KeyError(key)
                raise
            size = denc.loads(out)        # meta cells already excluded
            if size < self.k:
                self._rebalance(bound, entry)
            return

    def items(self) -> dict[str, bytes]:
        out: dict[str, bytes] = {}
        for _bound, entry in sorted(self._read_index().items()):
            raw = self._leaf_items(entry["oid"])
            if raw:
                out.update(self._user_items(raw))
        return out

    # -- structural ops ----------------------------------------------------

    def _mark_prefix(self, expect: dict[str, dict],
                     plan: dict) -> dict | None:
        """CAS the prefix marker onto every touched index entry.
        Returns the marked entries, or None if someone beat us."""
        marked = {}
        sets = {}
        exp = {}
        for bound, entry in expect.items():
            if entry.get("prefix"):
                return None
            new = dict(entry)
            new["prefix"] = {"ts": time.time(), **plan}
            marked[bound] = new
            exp[bound] = denc.dumps(entry)
            sets[bound] = denc.dumps(new)
        try:
            self.io.execute(self.index_oid, "kvstore", "update_index",
                            denc.dumps({"expect": exp, "set": sets}))
        except RadosError as e:
            if e.errno == 125:
                return None
            raise
        return marked

    def _kill_leaf(self, oid: str, ver: int) -> dict | None:
        """Bump the version and set the dead marker; returns the
        leaf's content (pre-image) or None if the guard lost."""
        raw = self._leaf_items(oid)
        if raw is None:
            return None
        try:
            self.io.execute(oid, "kvstore", "put_guarded", denc.dumps({
                "kv": {DEAD_KEY: b"1",
                       VER_KEY: str(ver + 1).encode()},
                "guard": {VER_KEY: str(ver).encode(), DEAD_KEY: None},
            }))
        except RadosError as e:
            if e.errno == 125:
                return None
            raise
        # the guard serialized us against every writer; the pre-image
        # plus nothing (writers now fail) is the authoritative content
        raw = self.io.get_omap(oid)
        return {k: v for k, v in raw.items()
                if not k.startswith("\x00")}

    def _write_leaf(self, oid: str, items: dict[str, bytes]) -> None:
        kv = {VER_KEY: b"1"}
        kv.update(items)
        self.io.execute(oid, "kvstore", "put_guarded", denc.dumps(
            {"kv": kv, "guard": {}}))

    def _stamp_final(self, marked: dict, final_sets: dict,
                     final_rm: list) -> dict | None:
        """Phase 2: atomically record the exact index transition in
        every marked entry.  From here on the op is roll-FORWARD-only;
        a healer that finds the stamp applies it verbatim."""
        exp = {}
        sets = {}
        stamped = {}
        final = {"set": dict(final_sets), "rm": list(final_rm)}
        for b, e in marked.items():
            ne = dict(e)
            ne["prefix"] = dict(e["prefix"])
            ne["prefix"]["final"] = final
            exp[b] = denc.dumps(e)
            sets[b] = denc.dumps(ne)
            stamped[b] = ne
        try:
            self.io.execute(self.index_oid, "kvstore", "update_index",
                            denc.dumps({"expect": exp, "set": sets}))
        except RadosError as e:
            if e.errno == 125:
                return None               # healer took over
            raise
        return stamped

    def _apply_final(self, stamped: dict, old_oids: list) -> None:
        """Phase 3: the commit point — swap the index to the recorded
        final state (clearing every marker) and delete the old
        leaves."""
        final = next(iter(stamped.values()))["prefix"]["final"]
        sets = {b: bytes(v) for b, v in final["set"].items()}
        rm = [b for b in final["rm"] if b not in sets]
        try:
            self.io.execute(self.index_oid, "kvstore", "update_index",
                            denc.dumps({
                                "expect": {b: denc.dumps(e)
                                           for b, e in stamped.items()},
                                "set": sets,
                                "rm": rm,
                            }))
        except RadosError as e:
            if e.errno != 125:
                raise
            return                        # healer finished it
        self._rm_objects(old_oids)

    def _rollback_all(self, marked: dict) -> None:
        for b, e in marked.items():
            orig = dict(e)
            orig.pop("prefix", None)
            self._rollback(b, e, orig)

    def _split(self, bound: str, entry: dict) -> None:
        """Split entry's leaf into two (kv_flat_btree_async.cc split:
        read, halve, write two, swap the index, delete the old)."""
        plan_new = [self._leaf_oid(), self._leaf_oid()]
        marked = self._mark_prefix(
            {bound: entry}, {"op": "split", "new": plan_new,
                             "old": [entry["oid"]],
                             "bounds": [bound]})
        if marked is None:
            return                        # someone else is on it
        content = self._kill_leaf(entry["oid"], entry["ver"])
        if content is None or len(content) < 2 * self.k:
            # raced shrink (or lost the kill): roll the marker back
            self._rollback_all(marked)
            return
        keys = sorted(content)
        half = len(keys) // 2
        self._write_leaf(plan_new[0],
                         {k: content[k] for k in keys[:half]})
        self._write_leaf(plan_new[1],
                         {k: content[k] for k in keys[half:]})
        lo_bound = _bound_key(keys[half - 1])
        stamped = self._stamp_final(marked, {
            lo_bound: denc.dumps({"oid": plan_new[0], "ver": 1}),
            bound: denc.dumps({"oid": plan_new[1], "ver": 1}),
        }, [])
        if stamped is not None:
            self._apply_final(stamped, [entry["oid"]])

    def _neighbor(self, idx: dict, bound: str) -> str | None:
        bounds = sorted(idx, key=lambda b: (b == INF, b))
        i = bounds.index(bound)
        if i + 1 < len(bounds):
            return bounds[i + 1]
        if i > 0:
            return bounds[i - 1]
        return None

    def _rebalance(self, bound: str, entry: dict) -> None:
        """Merge a thin leaf with a neighbor, or redistribute when the
        pair would immediately re-split (the reference's rebalance)."""
        idx = self._read_index()
        if idx.get(bound, {}).get("oid") != entry.get("oid"):
            return                        # stale view
        nbound = self._neighbor(idx, bound)
        if nbound is None:
            return                        # single leaf: nothing to do
        nentry = idx[nbound]
        if nentry.get("prefix") or idx[bound].get("prefix"):
            return
        lob, hib = sorted([bound, nbound],
                          key=lambda b: (b == INF, b))
        plan_new = [self._leaf_oid(), self._leaf_oid()]
        old_oids = [idx[bound]["oid"], nentry["oid"]]
        marked = self._mark_prefix(
            {bound: idx[bound], nbound: nentry},
            {"op": "merge", "new": plan_new, "old": old_oids,
             "bounds": [bound, nbound]})
        if marked is None:
            return
        c1 = self._kill_leaf(idx[bound]["oid"], idx[bound]["ver"])
        if c1 is None:
            self._rollback_all(marked)
            return
        c2 = self._kill_leaf(nentry["oid"], nentry["ver"])
        if c2 is None:
            # rollback resurrects the already-dead first leaf at a
            # fresh oid and clears both markers
            self._rollback_all(marked)
            return
        merged = {**c1, **c2}
        sets: dict[str, bytes] = {}
        rm: list[str] = []
        if len(merged) >= 2 * self.k:
            # redistribute: two fresh leaves, even halves
            keys = sorted(merged)
            half = len(keys) // 2
            self._write_leaf(plan_new[0],
                             {k: merged[k] for k in keys[:half]})
            self._write_leaf(plan_new[1],
                             {k: merged[k] for k in keys[half:]})
            sets[_bound_key(keys[half - 1])] = denc.dumps(
                {"oid": plan_new[0], "ver": 1})
            sets[hib] = denc.dumps({"oid": plan_new[1], "ver": 1})
            if lob != _bound_key(keys[half - 1]):
                rm.append(lob)
        else:
            self._write_leaf(plan_new[0], merged)
            sets[hib] = denc.dumps({"oid": plan_new[0], "ver": 1})
            rm.append(lob)
        stamped = self._stamp_final(marked, sets, rm)
        if stamped is not None:
            self._apply_final(stamped, old_oids)

    # -- crash healing -----------------------------------------------------

    def _rollback(self, bound: str, marked_entry: dict,
                  orig: dict) -> None:
        """Clear a marker, restoring the original entry.  If the old
        leaf was already killed, resurrect its content at a new oid."""
        entry = dict(orig)
        entry.pop("prefix", None)
        raw = self._leaf_items(entry["oid"])
        if raw is None:
            dead = self.io.get_omap(entry["oid"]) \
                if self._exists(entry["oid"]) else {}
            content = {k: v for k, v in dead.items()
                       if not k.startswith("\x00")}
            oid = self._leaf_oid()
            self._write_leaf(oid, content)
            old_oid = entry["oid"]
            entry = {"oid": oid, "ver": 1}
        else:
            old_oid = None
        try:
            self.io.execute(self.index_oid, "kvstore", "update_index",
                            denc.dumps({
                                "expect": {bound: denc.dumps(
                                    marked_entry)},
                                "set": {bound: denc.dumps(entry)},
                            }))
        except RadosError as e:
            if e.errno != 125:
                raise
            return
        if old_oid:
            self._rm_objects([old_oid])

    def _heal(self, bound: str, entry: dict) -> None:
        """A stale prefix marker.  The marker group (plan["bounds"])
        is gathered as one unit: if the final transition was stamped
        (phase 2 happened — atomic across the group) the op is past
        its point of no return and rolls FORWARD verbatim; otherwise
        every marked entry rolls BACK, resurrecting any killed leaf."""
        pfx = entry["prefix"]
        idx = self._read_index()
        group = {}
        for b in pfx.get("bounds", [bound]):
            e = idx.get(b)
            if (e is None or not e.get("prefix")
                    or e["prefix"].get("new") != pfx.get("new")):
                return                    # already resolved; re-walk
            group[b] = e
        if any(e["prefix"].get("final") for e in group.values()):
            self._apply_final(group, pfx.get("old", []))
        else:
            self._rollback_all(group)

    # -- misc --------------------------------------------------------------

    def _exists(self, oid: str) -> bool:
        try:
            self.io.stat(oid)
            return True
        except RadosError:
            return False

    def _rm_objects(self, oids) -> None:
        for oid in oids:
            try:
                self.io.remove_object(oid)
            except RadosError:
                pass

    # -- invariants (for tests / fsck) -------------------------------------

    def check_invariants(self) -> dict[str, int]:
        """Walk the tree; raise AssertionError on a broken invariant.
        Returns {leaves, entries}."""
        idx = self._read_index()
        assert idx, "index lost"
        assert INF in idx, "missing infinity bound"
        bounds = sorted((b for b in idx if b != INF))
        seen: set[str] = set()
        total = 0
        prev = ""
        for b in bounds + [INF]:
            entry = idx[b]
            assert entry.get("prefix") is None, \
                f"stale prefix marker on {b!r}"
            raw = self._leaf_items(entry["oid"])
            assert raw is not None, f"index points at dead leaf {b!r}"
            items = self._user_items(raw)
            assert not (set(items) & seen), "key in two leaves"
            seen |= set(items)
            total += len(items)
            if len(idx) > 1:
                assert len(items) <= 2 * self.k, \
                    f"leaf over 2k: {len(items)}"
            for k in sorted(items):
                bk = _bound_key(k)
                # prev carries ACROSS leaves: every key must sort after
                # the previous leaf's maximum or the global ordering
                # the bound index promises is broken
                assert bk > _bound_key(prev) or prev == "", \
                    f"key {k!r} out of order after {prev!r}"
                assert b == INF or bk <= b, \
                    f"key {k!r} outside its bound {b!r}"
                prev = k
        return {"leaves": len(idx), "entries": total}
