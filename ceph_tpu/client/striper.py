"""Striping: logical byte ranges RAID-0'd across objects.

The osdc/Striper.cc extent math + a libradosstriper-style API
(libradosstriper/RadosStriperImpl.cc): a logical "striped object" maps
onto `stripe_count` parallel object columns in stripe_unit blocks,
rolling to a new object set every `object_size` bytes per column.
Layout parameters mirror ceph_file_layout (su/sc/object_size); the
logical size lives in an xattr on the first object, as the reference
striper does.
"""

from __future__ import annotations

from dataclasses import dataclass

SIZE_XATTR = "striper.size"


@dataclass(frozen=True)
class Layout:
    """ceph_file_layout analog."""
    stripe_unit: int = 1 << 22        # 4 MiB
    stripe_count: int = 1
    object_size: int = 1 << 22

    def __post_init__(self):
        if self.object_size % self.stripe_unit:
            raise ValueError("object_size must be a multiple of "
                             "stripe_unit")
        if self.stripe_count < 1:
            raise ValueError("stripe_count >= 1")


@dataclass(frozen=True)
class Extent:
    """One contiguous piece of one backing object."""
    object_no: int
    offset: int          # within the object
    length: int
    logical_offset: int  # where this piece sits in the logical stream


def file_to_extents(layout: Layout, offset: int,
                    length: int) -> list[Extent]:
    """Striper::file_to_extents: logical [offset, offset+length) ->
    per-object extents."""
    out: list[Extent] = []
    su = layout.stripe_unit
    sc = layout.stripe_count
    stripes_per_object = layout.object_size // su
    pos = offset
    end = offset + length
    while pos < end:
        blockno = pos // su                   # stripe block index
        stripeno = blockno // sc              # full stripe row
        stripepos = blockno % sc              # column
        objectsetno = stripeno // stripes_per_object
        objectno = objectsetno * sc + stripepos
        block_start = (stripeno % stripes_per_object) * su
        block_off = pos % su
        x_off = block_start + block_off
        x_len = min(end - pos, su - block_off)
        out.append(Extent(objectno, x_off, x_len, pos))
        pos += x_len
    return out


def object_name(soid: str, object_no: int) -> str:
    return f"{soid}.{object_no:016x}"


class StripedObject:
    """Striped I/O over an IoCtx (libradosstriper surface)."""

    def __init__(self, ioctx, soid: str, layout: Layout | None = None):
        self.io = ioctx
        self.soid = soid
        self.layout = layout or Layout()

    def _size_holder(self) -> str:
        return object_name(self.soid, 0)

    def size(self) -> int:
        from .rados import RadosError
        try:
            blob = self.io.get_xattr(self._size_holder(), SIZE_XATTR)
            return int(blob.decode())
        except RadosError:
            return 0

    def _set_size(self, size: int) -> None:
        self.io.set_xattr(self._size_holder(), SIZE_XATTR,
                          str(size).encode())

    def write(self, data, offset: int = 0) -> None:
        """Fan the extents out as parallel aio writes.

        The payload rides as a BufferList rope: each extent's chunk is
        a zero-copy slice of the caller's buffer (Striper::file_to_
        extents + bufferlist::substr_of in the reference) instead of a
        per-extent bytes copy of the whole span."""
        from ..utils.bufferlist import BufferList, wrap_payload
        rope = BufferList(wrap_payload(data))
        extents = file_to_extents(self.layout, offset, len(rope))
        completions = []
        for ext in extents:
            chunk = rope.slice(ext.logical_offset - offset, ext.length)
            completions.append(self.io.aio_write(
                object_name(self.soid, ext.object_no), chunk,
                offset=ext.offset))
        for c in completions:
            c.wait_for_complete()
        for c in completions:
            c.result()          # raise the first failure
        end = offset + len(data)
        if end > self.size():
            # ensure the size holder exists even when object 0 holds
            # no data (write at a far offset)
            if not any(e.object_no == 0 for e in extents):
                self.io.aio_write(object_name(self.soid, 0), b"",
                                  offset=0).wait_for_complete()
            self._set_size(end)

    def read(self, offset: int = 0, length: int = 0):
        """Striped read, reassembled ZERO-COPY.

        ``file_to_extents`` tiles [offset, offset+length) contiguously
        in logical order, so reassembly is rope concatenation: each
        extent's reply rides in as a shared segment (the old
        ``bytearray(length)`` staging buffer copied every byte once),
        with sparse holes (ENOENT / short object tails) zero-filled.
        Returns a :class:`~ceph_tpu.utils.bufferlist.BufferList`
        (compares equal to bytes; ``bytes(r)`` is the audited
        flatten for consumers that need contiguity)."""
        from ..utils.bufferlist import BufferList
        size = self.size()
        if length == 0 or offset + length > size:
            length = max(0, size - offset)
        rope = BufferList()
        if length == 0:
            return rope
        extents = file_to_extents(self.layout, offset, length)
        completions = [
            (ext, self.io.aio_read(object_name(self.soid, ext.object_no),
                                   length=ext.length, offset=ext.offset))
            for ext in extents]
        from .rados import RadosError
        for ext, c in completions:
            c.wait_for_complete()
            try:
                piece = c.result()
            except RadosError as e:
                if e.errno != 2:
                    raise      # only ENOENT means "sparse, read zeros"
                piece = b""
            if len(piece) > ext.length:
                piece = memoryview(piece)[: ext.length]
            rope.append(piece)
            if len(piece) < ext.length:
                # hole: unwritten object / short tail reads as zeros
                rope.append(b"\0" * (ext.length - len(piece)))
        return rope

    def remove(self) -> None:
        """List backing objects by prefix rather than deriving them
        from the size xattr: a write that failed before updating the
        size must not leak its already-written extents."""
        import re
        from .rados import RadosError
        # exactly <soid>.<16 hex digits>: a bare prefix match would
        # also destroy 'vol.backup.*' when removing 'vol'
        pat = re.compile(re.escape(self.soid) + r"\.[0-9a-f]{16}$")
        names = [n for n in self.io.list_objects() if pat.fullmatch(n)]
        for name in set(names) | {self._size_holder()}:
            try:
                self.io.remove_object(name)
            except RadosError:
                pass

    def stat(self) -> dict:
        return {"size": self.size()}
