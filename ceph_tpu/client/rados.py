"""librados-style public API: Rados (cluster handle) + IoCtx (per pool).

Mirrors the reference's librados surface (librados/librados.cc /
pybind rados.pyx): connect, pool ops, synchronous object I/O with the
same call names (write, write_full, append, read, stat, remove,
get/set_xattr, omap).  Errors raise RadosError with the errno.
"""

from __future__ import annotations

import itertools
import threading

from ..mon.client import MonClient
from ..mon.monmap import MonMap
from ..msg import create_messenger
from ..utils.bufferlist import wrap_payload
from ..utils.config import Config
from .objecter import Objecter, ObjecterError


class RadosError(Exception):
    def __init__(self, errno_: int, msg: str = ""):
        super().__init__(msg or f"errno {errno_}")
        self.errno = errno_


class Completion:
    """aio completion handle (librados::AioCompletion)."""

    def __init__(self):
        self._event = threading.Event()
        self._result = None
        self._exc: Exception | None = None
        self._callback = None
        self._cb_fired = False
        self._lock = threading.Lock()

    def set_callback(self, fn) -> "Completion":
        # lock against _finish: without it the callback can fire from
        # both paths (finish sees it set, then we see the event set)
        with self._lock:
            self._callback = fn
            fire = self._event.is_set() and not self._cb_fired
            if fire:
                self._cb_fired = True
        if fire:
            fn(self)
        return self

    def _finish(self, result=None, exc: Exception | None = None) -> None:
        self._result = result
        self._exc = exc
        with self._lock:
            self._event.set()
            cb = self._callback if not self._cb_fired else None
            if cb is not None:
                self._cb_fired = True
        if cb is not None:
            try:
                cb(self)
            except Exception:
                pass

    def is_complete(self) -> bool:
        return self._event.is_set()

    def wait_for_complete(self, timeout: float | None = None) -> bool:
        return self._event.wait(timeout)

    def result(self):
        """The op's return value; raises the op's error."""
        self._event.wait()
        if self._exc is not None:
            raise self._exc
        return self._result


class Rados:
    def __init__(self, monmap: MonMap, name: str = "client.admin",
                 conf: Config | None = None):
        self.conf = conf or Config()
        from ..utils.dout import DoutLogger
        self.log = DoutLogger("rados", name)
        self.msgr = create_messenger(name, conf=self.conf)
        self.msgr.bind(("127.0.0.1", 0))
        self.monc: MonClient | None = None
        self.objecter: Objecter | None = None
        self.monmap = monmap
        self._connected = False
        # watch callbacks: (oid, cookie) -> fn(notify_id, payload)->bytes
        self.watches: dict[tuple, object] = {}
        # (oid, cookie) -> pool_id: enough to re-assert registrations
        # after map changes (primaries hold watches in memory only)
        self._watch_pools: dict[tuple, int] = {}
        # aio executor: thread-backed async (the reference's aio is
        # event-driven inside the Objecter; here the sync state machine
        # — with its EAGAIN/resend handling — runs on worker threads,
        # which keeps identical retry semantics for async callers)
        from concurrent.futures import ThreadPoolExecutor
        self._aio_pool = ThreadPoolExecutor(
            max_workers=16, thread_name_prefix=f"aio-{name}")

    def aio_submit(self, fn, *args, **kwargs) -> Completion:
        comp = Completion()

        def run():
            try:
                comp._finish(result=fn(*args, **kwargs))
            except Exception as e:
                comp._finish(exc=e)

        self._aio_pool.submit(run)
        return comp

    def _rewatch_on_map(self, osdmap) -> None:
        """Watches are primary-memory state: a new primary (or a
        restarted one) has never heard of ours, so re-register on every
        map change — the linger-op model, off the delivery thread."""
        if not self._watch_pools:
            return

        def rewatch(attempt: int = 0):
            failed = False
            for (oid, cookie), pool_id in list(self._watch_pools.items()):
                try:
                    self.objecter.op_submit(
                        pool_id, oid, [("watch", cookie)], timeout=10.0)
                except Exception as e:
                    # keep trying THIS one but continue with the rest:
                    # one stuck watch must not starve the others, and a
                    # silent drop loses every future notify
                    failed = True
                    self.log.warn("rewatch %s/%s failed: %s%s",
                                  pool_id, oid, e,
                                  " (will retry)" if attempt < 3 else "")
            if failed and attempt < 3:
                t = threading.Timer(5.0, rewatch,
                                    kwargs={"attempt": attempt + 1})
                t.daemon = True
                t.start()

        threading.Thread(target=rewatch, daemon=True,
                         name="rewatch").start()

    def ms_dispatch(self, conn, msg) -> bool:
        from ..osd.messages import MWatchNotify
        if isinstance(msg, MWatchNotify):
            # callbacks run OFF the messenger delivery loop: a callback
            # that issues rados ops (the cls_lock renew pattern) would
            # otherwise deadlock the thread that delivers its replies
            threading.Thread(
                target=self._run_watch_cb,
                args=(conn.peer_name, conn.peer_addr, msg),
                daemon=True, name="watch-cb").start()
            return True
        return False

    def _run_watch_cb(self, peer_name, peer_addr, msg) -> None:
        from ..osd.messages import MWatchNotifyAck
        cb = self.watches.get((msg.oid, int(msg.cookie)))
        reply = b""
        if cb is not None:
            try:
                reply = cb(msg.notify_id, msg.payload) or b""
            except Exception:
                pass
        self.msgr.send_message(
            MWatchNotifyAck(oid=msg.oid, pgid=msg.pgid,
                            notify_id=msg.notify_id,
                            cookie=msg.cookie, reply=reply),
            peer_name, peer_addr)

    def ms_handle_reset(self, conn) -> None:
        pass

    def connect(self, timeout: float = 30.0) -> None:
        self.msgr.start()
        self.msgr.add_dispatcher_tail(self)
        self.monc = MonClient(self.msgr, self.monmap)
        self.objecter = Objecter(self.msgr, self.monc)
        if self.msgr.auth_mode == "cephx":
            # TGS flow: fetch + renew service tickets for the daemons
            # we dial (CephxClientHandler); the mon channel itself
            # stays on the static keyring secret
            self.monc.enable_service_auth(
                [self.msgr], own_service=None,
                ticket_services=["osd", "mds"])
        self.objecter.on_map_hooks.append(self._rewatch_on_map)
        self.monc.sub_want_osdmap(0)
        self.monc.subscribe({"monmap": 0})   # learn membership changes
        deadline = threading.Event()
        import time
        end = time.time() + timeout
        while time.time() < end and self.monc.osdmap.epoch == 0:
            time.sleep(0.05)
        if self.monc.osdmap.epoch == 0:
            raise RadosError(110, "could not fetch osdmap from monitors")
        self._connected = True

    def shutdown(self) -> None:
        # cancel queued aio: running it against the shut-down messenger
        # would stall atexit's executor join for a full op timeout
        self._aio_pool.shutdown(wait=False, cancel_futures=True)
        if self.monc is not None:
            self.monc.shutdown()
        self.msgr.shutdown()
        self._connected = False

    # -- cluster admin -----------------------------------------------------

    def mon_command(self, cmd: dict, timeout: float = 30.0):
        rv, out, data = self.monc.command(cmd, timeout=timeout)
        return rv, out, data

    def create_pool(self, name: str, pg_num: int = 8, **kw) -> None:
        cmd = {"prefix": "osd pool create", "pool": name,
               "pg_num": pg_num, **kw}
        rv, out, _ = self.mon_command(cmd)
        if rv != 0:
            raise RadosError(-rv if rv < 0 else rv, out)
        self._wait_for_pool(name)

    def create_ec_pool(self, name: str, profile_name: str,
                       profile: dict | None = None, pg_num: int = 8) -> None:
        if profile:
            toks = [f"{k}={v}" for k, v in profile.items()]
            rv, out, _ = self.mon_command({
                "prefix": "osd erasure-code-profile set",
                "name": profile_name, "profile": toks})
            if rv != 0:
                raise RadosError(abs(rv), out)
        rv, out, _ = self.mon_command({
            "prefix": "osd pool create", "pool": name, "pg_num": pg_num,
            "pool_type": "erasure", "erasure_code_profile": profile_name})
        if rv != 0:
            raise RadosError(abs(rv), out)
        self._wait_for_pool(name)

    def _wait_for_pool(self, name: str, timeout: float = 10.0) -> None:
        import time
        end = time.time() + timeout
        while time.time() < end:
            if self.monc.osdmap.pool_by_name(name):
                return
            self.monc.sub_want_osdmap(self.monc.osdmap.epoch + 1)
            time.sleep(0.1)
        raise RadosError(110, f"pool {name} did not appear")

    def delete_pool(self, name: str) -> None:
        rv, out, _ = self.mon_command({"prefix": "osd pool rm",
                                       "pool": name})
        if rv != 0:
            raise RadosError(abs(rv), out)

    def list_pools(self) -> list[str]:
        rv, out, _ = self.mon_command({"prefix": "osd pool ls"})
        return out.split("\n") if out else []

    def open_ioctx(self, pool_name: str) -> "IoCtx":
        pool = self.monc.osdmap.pool_by_name(pool_name)
        if pool is None:
            raise RadosError(2, f"no such pool {pool_name}")
        return IoCtx(self, pool.id, pool_name)

    def status(self) -> str:
        rv, out, _ = self.mon_command({"prefix": "status"})
        return out


class IoCtx:
    def __init__(self, rados: Rados, pool_id: int, pool_name: str):
        self.rados = rados
        self.pool_id = pool_id
        self.pool_name = pool_name
        # self-managed snap context (librados set_snap_context model):
        # writes carry it; the OSD clones the head when it has snaps
        # newer than the object's SnapSet
        self.snap_seq = 0
        self.snaps: list[int] = []

    def _op(self, oid: str, ops: list, timeout: float | None = None,
            snapid=None):
        # timeout None -> the objecter's objecter_op_timeout default
        snapc = (self.snap_seq, list(self.snaps)) if self.snap_seq \
            else None
        try:
            reply = self.rados.objecter.op_submit(self.pool_id, oid, ops,
                                                  timeout, snapc=snapc,
                                                  snapid=snapid)
        except ObjecterError as e:
            raise RadosError(e.errno, str(e)) from e
        if reply.result < 0:
            raise RadosError(-reply.result,
                             f"op on {oid}: errno {-reply.result}")
        return reply

    # -- self-managed snapshots --------------------------------------------

    def set_snap_context(self, seq: int, snaps: list[int]) -> None:
        self.snap_seq = int(seq)
        self.snaps = sorted(int(s) for s in snaps)[::-1]

    def create_selfmanaged_snap(self) -> int:
        """Allocate a snap id AND fold it into the local context."""
        ret, out, data = self.rados.mon_command(
            {"prefix": "osd pool selfmanaged-snap create",
             "pool": self.pool_name})
        if ret != 0:
            raise RadosError(-ret or 5, out)
        snapid = int(out)
        self.set_snap_context(snapid, [snapid] + self.snaps)
        return snapid

    def remove_selfmanaged_snap(self, snapid: int) -> None:
        ret, out, _ = self.rados.mon_command(
            {"prefix": "osd pool selfmanaged-snap rm",
             "pool": self.pool_name, "snapid": int(snapid)})
        if ret != 0:
            raise RadosError(-ret or 5, out)
        self.snaps = [s for s in self.snaps if s != int(snapid)]

    def snap_read(self, oid: str, snapid: int, length: int = 0,
                  offset: int = 0) -> bytes:
        reply = self._op(oid, [("read", offset, length)], snapid=snapid)
        return reply.outdata[0]

    def snap_rollback(self, oid: str, snapid: int) -> None:
        self._op(oid, [("rollback", int(snapid))])

    # -- aio (librados aio_* surface, thread-backed) -----------------------

    def aio_write(self, oid: str, data: bytes, offset: int = 0):
        return self.rados.aio_submit(self.write, oid, data, offset)

    def aio_write_full(self, oid: str, data: bytes):
        return self.rados.aio_submit(self.write_full, oid, data)

    def aio_append(self, oid: str, data: bytes):
        return self.rados.aio_submit(self.append, oid, data)

    def aio_read(self, oid: str, length: int = 0, offset: int = 0):
        return self.rados.aio_submit(self.read, oid, length, offset)

    def aio_remove(self, oid: str):
        return self.rados.aio_submit(self.remove_object, oid)

    def aio_stat(self, oid: str):
        return self.rados.aio_submit(self.stat, oid)

    def aio_execute(self, oid: str, cls: str, method: str,
                    data: bytes = b""):
        return self.rados.aio_submit(self.execute, oid, cls, method,
                                     data)

    # -- striping (libradosstriper surface) --------------------------------

    def striped(self, soid: str, layout=None):
        from .striper import StripedObject
        return StripedObject(self, soid, layout)

    # -- object classes (in-OSD RPC) ---------------------------------------

    def execute(self, oid: str, cls: str, method: str,
                data: bytes = b"") -> bytes | None:
        """Run a registered class method on the object (rados exec)."""
        reply = self._op(oid, [("call", cls, method, bytes(data))])
        return reply.outdata[0] if reply.outdata else None

    # -- watch / notify ----------------------------------------------------

    _cookie_seq = itertools.count(1)    # next() is atomic in CPython

    def watch(self, oid: str, callback) -> int:
        """callback(notify_id, payload) -> optional reply bytes.
        Returns the watch cookie (handle for unwatch)."""
        cookie = next(IoCtx._cookie_seq)
        self.rados.watches[(oid, cookie)] = callback
        self.rados._watch_pools[(oid, cookie)] = self.pool_id
        try:
            self._op(oid, [("watch", cookie)])
        except RadosError:
            self.rados.watches.pop((oid, cookie), None)
            self.rados._watch_pools.pop((oid, cookie), None)
            raise
        return cookie

    def unwatch(self, oid: str, cookie: int) -> None:
        self.rados.watches.pop((oid, cookie), None)
        self.rados._watch_pools.pop((oid, cookie), None)
        self._op(oid, [("unwatch", cookie)])

    def notify(self, oid: str, payload: bytes = b"",
               timeout: float = 5.0) -> dict:
        """Returns {watcher: reply_bytes} gathered from all watchers."""
        reply = self._op(oid, [("notify", bytes(payload), timeout)],
                         timeout=timeout + 10.0)
        return reply.outdata[0] if reply.outdata else {}

    # -- writes ------------------------------------------------------------
    #
    # Payloads ride ZERO-COPY: bytes/memoryview/BufferList pass through
    # untouched all the way to the messenger's gather write (the
    # objecter snapshots only mutable bytearrays).  Build large
    # payloads as a utils.bufferlist.BufferList rope to concatenate
    # and slice without materializing.

    def write(self, oid: str, data, offset: int = 0) -> None:
        self._op(oid, [("write", offset, wrap_payload(data))])

    def write_full(self, oid: str, data) -> None:
        self._op(oid, [("writefull", wrap_payload(data))])

    def append(self, oid: str, data) -> None:
        self._op(oid, [("append", wrap_payload(data))])

    def remove_object(self, oid: str) -> None:
        self._op(oid, [("delete",)])

    def truncate(self, oid: str, size: int) -> None:
        self._op(oid, [("truncate", size)])

    def set_xattr(self, oid: str, name: str, value: bytes) -> None:
        self._op(oid, [("setxattr", name, bytes(value))])

    def set_omap(self, oid: str, kv: dict) -> None:
        self._op(oid, [("omap_set", {k: bytes(v) for k, v in kv.items()})])

    def rm_omap_keys(self, oid: str, keys: list[str]) -> None:
        self._op(oid, [("omap_rm", list(keys))])

    # -- reads -------------------------------------------------------------

    def read(self, oid: str, length: int = 0, offset: int = 0) -> bytes:
        reply = self._op(oid, [("read", offset, length)])
        return reply.outdata[0]

    def stat(self, oid: str) -> dict:
        reply = self._op(oid, [("stat",)])
        return reply.outdata[0]

    def get_xattr(self, oid: str, name: str) -> bytes:
        reply = self._op(oid, [("getxattr", name)])
        return reply.outdata[0]

    def get_omap(self, oid: str) -> dict:
        reply = self._op(oid, [("omap_get",)])
        return reply.outdata[0]

    def get_omap_keys(self, oid: str, keys: list[str]) -> dict:
        """Only the named keys (omap_get_vals_by_keys): O(requested),
        not O(omap)."""
        reply = self._op(oid, [("omap_get_keys", list(keys))])
        return reply.outdata[0]

    def get_omap_vals(self, oid: str, start_after: str = "",
                      prefix: str = "", max_return: int = 0) -> dict:
        """Ordered omap slice (omap_get_vals): keys strictly after
        start_after, prefix-filtered, bounded — the pagination
        primitive bucket listings ride."""
        reply = self._op(oid, [("omap_get_vals", start_after, prefix,
                                int(max_return))])
        return reply.outdata[0]

    def list_objects(self) -> list[str]:
        """Scan every pg of the pool (pool listing = union of pg scans)."""
        from ..osd.osdmap import PgId
        seen = set()
        m = self.rados.monc.osdmap
        pool = m.pools[self.pool_id]
        for seed in range(pool.pg_num):
            pgid = PgId(self.pool_id, seed)
            try:
                reply = self.rados.objecter.op_submit(
                    self.pool_id, "", [("list",)], pgid=pgid)
            except ObjecterError:
                continue
            if reply.result == 0:
                seen.update(reply.outdata[0])
        return sorted(seen)
