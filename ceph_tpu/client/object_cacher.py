"""ObjectCacher: client-side object data cache (osdc/ObjectCacher.cc
reduced).

The reference's write-back page cache sits between librbd/the fs
client and the Objecter: reads are served from cached extents, writes
buffer as dirty extents flushed asynchronously, bounded by dirty/clean
byte budgets.  This keeps that shape with simpler machinery:

  * per-object sorted extent map (offset -> bytearray), adjacent and
    overlapping runs merged on insert;
  * reads call `fetch` only for the gaps, then serve one contiguous
    buffer; a fetch's result is inserted clean;
  * writes overlay dirty extents; flush() pushes dirty runs through
    the `writer` callback in offset order and marks them clean;
  * byte-budget LRU across objects evicts CLEAN extents only — dirty
    data never silently drops (BufferHead states reduced to
    clean/dirty).

Consistency contract (same as the reference's librbd usage): one
writer at a time — librbd guards the cache with the exclusive lock,
snapshots/flatten flush first.  Shared concurrent writers must run
uncached.
"""

from __future__ import annotations

import threading
from typing import Callable


class _Object:
    __slots__ = ("extents", "dirty")

    def __init__(self):
        self.extents: dict[int, bytearray] = {}   # start -> bytes
        self.dirty: set[tuple[int, int]] = set()  # (start, len) runs


class ObjectCacher:
    def __init__(self, max_size: int = 32 << 20,
                 max_dirty: int = 16 << 20,
                 writer: Callable[[str, int, bytes], None] | None = None):
        self.max_size = max_size
        self.max_dirty = max_dirty
        self.writer = writer
        self._objects: dict[str, _Object] = {}    # insertion = LRU
        self._lock = threading.RLock()
        self.hits = 0
        self.misses = 0

    # -- bookkeeping -------------------------------------------------------

    def _obj(self, oid: str) -> _Object:
        obj = self._objects.pop(oid, None)
        if obj is None:
            obj = _Object()
        self._objects[oid] = obj                  # move to MRU end
        return obj

    def _size_of(self, obj: _Object) -> int:
        return sum(len(b) for b in obj.extents.values())

    def size(self) -> int:
        with self._lock:
            return sum(self._size_of(o) for o in self._objects.values())

    def dirty_bytes(self) -> int:
        with self._lock:
            return sum(ln for o in self._objects.values()
                       for (_s, ln) in o.dirty)

    # -- extent algebra ----------------------------------------------------

    @staticmethod
    def _insert(obj: _Object, off: int, data: bytes) -> None:
        """Overlay [off, off+len) and merge touching runs."""
        start, buf = off, bytearray(data)
        merged = True
        while merged:
            merged = False
            for s in list(obj.extents):
                b = obj.extents[s]
                e, be = start + len(buf), s + len(b)
                if be < start or e < s:
                    continue                       # disjoint
                del obj.extents[s]
                ns = min(s, start)
                nb = bytearray(max(be, e) - ns)
                nb[s - ns: s - ns + len(b)] = b
                nb[start - ns: start - ns + len(buf)] = buf
                start, buf = ns, nb
                merged = True
                break
        obj.extents[start] = buf

    @staticmethod
    def _covered(obj: _Object, off: int, length: int) -> bool:
        for s, b in obj.extents.items():
            if s <= off and off + length <= s + len(b):
                return True
        return False

    @staticmethod
    def _read_cached(obj: _Object, off: int, length: int) -> bytes:
        for s, b in obj.extents.items():
            if s <= off and off + length <= s + len(b):
                return bytes(b[off - s: off - s + length])
        raise KeyError(off)

    # -- public API --------------------------------------------------------

    def try_read(self, oid: str, off: int,
                 length: int) -> bytes | None:
        """Cache-only probe: the bytes on a hit, None on a miss."""
        with self._lock:
            obj = self._obj(oid)
            if self._covered(obj, off, length):
                self.hits += 1
                return self._read_cached(obj, off, length)
            self.misses += 1
            return None

    def insert_clean(self, oid: str, off: int, data: bytes,
                     length: int) -> bytes:
        """Install fetched bytes (padded to `length`) WITHOUT
        clobbering dirty overlays — buffered writes always win over
        whatever the fetch returned.  Returns the post-merge bytes."""
        with self._lock:
            obj = self._obj(oid)
            end = off + length
            overlays = []
            for (s, ln) in obj.dirty:
                if s < end and s + ln > off:
                    try:
                        overlays.append(
                            (s, self._read_cached(obj, s, ln)))
                    except KeyError:
                        pass     # trimmed by a racing discard
            self._insert(obj, off, bytes(data).ljust(length, b"\0"))
            for s, b in overlays:
                self._insert(obj, s, b)
            out = self._read_cached(obj, off, length)
            self._evict_clean()
            return out

    def read(self, oid: str, off: int, length: int,
             fetch: Callable[[int, int], bytes]) -> bytes:
        """Serve [off, off+length); `fetch(off, length)` fills the
        whole range on a miss (fetch granularity is the caller's —
        librbd fetches the full extent, so one miss warms the run)."""
        got = self.try_read(oid, off, length)
        if got is not None:
            return got
        return self.insert_clean(oid, off, fetch(off, length), length)

    def write(self, oid: str, off: int, data: bytes) -> None:
        """Buffer a dirty extent (write-back).  Flushes synchronously
        through `writer` when the dirty budget is exceeded."""
        with self._lock:
            obj = self._obj(oid)
            self._insert(obj, off, data)
            obj.dirty.add((off, len(data)))
            over = self.dirty_bytes() > self.max_dirty
        if over:
            self.flush()

    def flush(self, oid: str | None = None) -> int:
        """Push dirty runs through `writer` in offset order."""
        if self.writer is None:
            raise RuntimeError("no writer wired; cache is read-only")
        flushed = 0
        with self._lock:
            targets = [oid] if oid is not None else list(self._objects)
            work = []
            for o in targets:
                obj = self._objects.get(o)
                if obj is None or not obj.dirty:
                    continue
                work.append((o, obj, sorted(obj.dirty)))
        for o, obj, runs in work:
            for s, ln in runs:
                with self._lock:
                    try:
                        data = self._read_cached(obj, s, ln)
                    except KeyError:
                        obj.dirty.discard((s, ln))
                        continue     # discard raced; gone
                # a run stays DIRTY until its write succeeds: a
                # transient writer failure must retry on the next
                # flush, not silently launder the data clean
                self.writer(o, s, data)
                with self._lock:
                    obj.dirty.discard((s, ln))
                flushed += ln
        return flushed

    def discard(self, oid: str, off: int | None = None,
                length: int | None = None) -> None:
        """Drop cached state (dirty included — the caller just
        truncated/removed the backing object)."""
        with self._lock:
            if off is None:
                self._objects.pop(oid, None)
                return
            obj = self._objects.get(oid)
            if obj is None:
                return
            end = off + (length or 0)
            for s in list(obj.extents):
                b = obj.extents[s]
                if s + len(b) <= off or (length is not None and s >= end):
                    continue
                del obj.extents[s]
                if s < off:
                    obj.extents[s] = b[: off - s]
                if length is not None and s + len(b) > end:
                    obj.extents[end] = b[end - s:]
            # trim straddling dirty runs instead of dropping them —
            # the un-discarded portion is still unflushed data
            new_dirty: set[tuple[int, int]] = set()
            for (s, ln) in obj.dirty:
                e = s + ln
                if e <= off or (length is not None and s >= end):
                    new_dirty.add((s, ln))
                    continue
                if s < off:
                    new_dirty.add((s, off - s))
                if length is not None and e > end:
                    new_dirty.add((end, e - end))
            obj.dirty = new_dirty

    def invalidate_all(self) -> None:
        with self._lock:
            self._objects.clear()

    def _evict_clean(self) -> None:
        """LRU-evict CLEAN objects past the byte budget (dirty data is
        never dropped; flush first)."""
        total = sum(self._size_of(o) for o in self._objects.values())
        if total <= self.max_size:
            return
        for oid in list(self._objects):
            obj = self._objects[oid]
            if obj.dirty:
                continue
            total -= self._size_of(obj)
            del self._objects[oid]
            if total <= self.max_size:
                return
