"""Objecter: client op targeting + resend state machine.

The osdc/Objecter.{h,cc} analog: each op computes its target pg/primary
from the current OSDMap client-side (CRUSH — no lookup service), sends
MOSDOp, and resends on map change or EAGAIN from a stale/degraded
primary (op_submit/_calc_target/_send_op semantics, Objecter.cc:2289,
2661, 3078).  Ops carry a budget throttle like the reference's.
"""

from __future__ import annotations

import itertools
import threading
from typing import Any

from ..mon.client import MonClient
from ..msg import Dispatcher, Message, Messenger
from ..osd.messages import MOSDOp, MOSDOpReply
from ..osd.osdmap import OSDMap
from ..utils.bufferlist import BufferList, wrap_payload
from ..utils.dout import DoutLogger
from ..utils.throttle import Throttle

# the defined errno an op fails with when its deadline exhausts
# (ETIMEDOUT — the rados_osd_op_timeout contract)
ETIMEDOUT = 110


class ObjecterError(Exception):
    def __init__(self, errno_: int, msg: str = ""):
        super().__init__(msg or f"errno {errno_}")
        self.errno = errno_


class _Op:
    __slots__ = ("tid", "pool", "oid", "ops", "event", "reply", "attempts",
                 "pgid", "snapc", "snapid")

    def __init__(self, tid, pool, oid, ops, pgid=None, snapc=None,
                 snapid=None):
        self.tid = tid
        self.pool = pool
        self.oid = oid
        self.ops = ops
        self.pgid = pgid            # explicit target (pg listing ops)
        self.snapc = snapc          # (seq, [snaps]) write snap context
        self.snapid = snapid        # read-at-snap
        self.event = threading.Event()
        self.reply = None
        self.attempts = 0


class Objecter(Dispatcher):
    def __init__(self, msgr: Messenger, monc: MonClient):
        self.msgr = msgr
        self.monc = monc
        self.conf = msgr.conf
        self.log = DoutLogger("objecter", msgr.name)
        self._tid = itertools.count(1)
        self._ops: dict[int, _Op] = {}
        self._lock = threading.Lock()
        self.throttle = Throttle("objecter-ops", 1024)
        self.on_map_hooks: list = []     # linger-ish: rewatch etc.
        msgr.add_dispatcher_head(self)
        monc.on_osdmap = self._on_map

    @property
    def osdmap(self) -> OSDMap:
        return self.monc.osdmap

    # -- submission --------------------------------------------------------

    def op_submit(self, pool_id: int, oid: str, ops: list,
                  timeout: float | None = None, pgid=None, snapc=None,
                  snapid=None) -> Message:
        """Submit and wait, bounded by a per-op deadline.

        The op resends for as long as it lives (Objecter::_op_submit +
        _maybe_request_map, osdc/Objecter.cc:2289, 2661) on an
        EXPONENTIAL backoff (objecter_backoff_base doubling to
        objecter_backoff_max): every silent try re-requests newer maps,
        and after objecter_silent_kick seconds of CONTINUOUS silence on
        the same primary's link the connection is marked down so the
        resend dials a fresh socket — an opaque wedge in a long-lived
        session must cost one reconnect, not the whole op.  The kick is
        time-based, not try-based: with fast early retries a try-count
        would kill a merely-slow link in ~1.5s and drop its in-flight
        reply, turning one slow op into a resend convoy.  On deadline
        exhaustion the op fails with the DEFINED errno ETIMEDOUT
        (110); an op whose OSD dies mid-flight can never hang forever,
        even if no new osdmap arrives."""
        import time
        if timeout is None:
            timeout = float(self.conf.objecter_op_timeout)
        self.throttle.get(1, timeout=timeout)
        try:
            # zero-copy payload contract: ops may carry bytes,
            # memoryview or BufferList payloads that ride untouched to
            # the messenger's gather write.  An op outlives this call's
            # frame (map-change resends re-encode it), so mutable
            # bytearrays are snapshotted HERE — the single defense
            # point for every client surface.
            ops = [tuple(wrap_payload(f) if isinstance(
                f, (bytes, bytearray, memoryview, BufferList)) else f
                for f in op) for op in ops]
            op = _Op(next(self._tid), pool_id, oid, ops, pgid,
                     snapc=snapc, snapid=snapid)
            with self._lock:
                self._ops[op.tid] = op
            deadline = time.monotonic() + timeout
            base = max(0.05, float(self.conf.objecter_backoff_base))
            bmax = max(base, float(self.conf.objecter_backoff_max))
            kick_after = max(2 * base,
                             float(self.conf.objecter_silent_kick))
            backoff = base
            silent_for = 0.0
            last_primary = None
            while True:
                remain = deadline - time.monotonic()
                if remain <= 0:
                    break
                primary = self._send(op)
                sent = primary is not None
                if primary != last_primary:
                    # retargeted (map change): the silence clock and
                    # the backoff curve belong to the OLD link — a
                    # fresh primary gets its full fast tries before
                    # its conn is suspected
                    silent_for = 0.0
                    backoff = base
                    last_primary = primary
                if not sent:
                    # no primary yet (pool absent / not enough osds):
                    # ask for newer maps and wait for one to arrive
                    self.monc.sub_want_osdmap(self.osdmap.epoch + 1)
                waited = min(backoff, remain)
                if op.event.wait(waited):
                    reply = op.reply
                    if reply.result == -11:     # EAGAIN: resend later
                        op.event.clear()
                        op.reply = None
                        silent_for = 0.0
                        backoff = base
                        time.sleep(0.2)
                        self.monc.sub_want_osdmap(self.osdmap.epoch + 1)
                        continue
                    with self._lock:
                        self._ops.pop(op.tid, None)
                    return reply
                op.event.clear()
                backoff = min(backoff * 2, bmax)
                if sent:
                    silent_for += waited
                    self.monc.sub_want_osdmap(self.osdmap.epoch + 1)
                    if silent_for >= kick_after:
                        # nothing heard on this link for the whole
                        # kick window: assume the session is wedged
                        # and force a reconnect (PG-side reqid dedup
                        # makes the re-execution safe)
                        silent_for = 0.0
                        self._kick_target(primary, op.tid)
            with self._lock:
                self._ops.pop(op.tid, None)
            raise ObjecterError(
                ETIMEDOUT,
                f"op on {oid} timed out after {timeout:.1f}s "
                f"({op.attempts} attempts)")
        finally:
            self.throttle.put(1)

    def _kick_target(self, primary: int, tid: int) -> None:
        """Mark down the connection to the op's silent primary."""
        conn = self.msgr.conns.get(f"osd.{primary}")
        if conn is not None:
            self.log.warn("op %d silent to osd.%d: marking conn down",
                          tid, primary)
            conn.mark_down()

    @staticmethod
    def _is_write(ops: list) -> bool:
        from ..cls import registry as cls_registry
        for op in ops:
            if op[0] in ("read", "stat", "getxattr", "getxattrs",
                         "omap_get", "list"):
                continue
            if op[0] == "call" and not cls_registry.is_write(op[1], op[2]):
                continue
            return True
        return False

    def _target_pool(self, op: _Op) -> int:
        """Cache-tier overlay redirect (Objecter::_calc_target
        consulting pg_pool_t read_tier/write_tier, Objecter.cc:2661):
        ops aimed at a base pool with an overlay go to the tier pool;
        in readonly mode only reads are diverted."""
        pool = self.osdmap.pools.get(op.pool)
        if pool is None or (pool.read_tier < 0 and pool.write_tier < 0):
            return op.pool
        if self._is_write(op.ops):
            tier = self.osdmap.pools.get(pool.write_tier)
            if tier is not None and tier.cache_mode == "writeback":
                return tier.id
            return op.pool
        tier = self.osdmap.pools.get(pool.read_tier)
        if tier is not None and tier.cache_mode in ("writeback",
                                                    "readonly"):
            return tier.id
        return op.pool

    def _send(self, op: _Op) -> int | None:
        """Send to the current target; return the primary osd id, or
        None when the op cannot be targeted yet (pool absent, no
        primary, no address)."""
        m = self.osdmap
        if op.pool not in m.pools:
            return None
        pgid = op.pgid if op.pgid is not None else \
            m.object_to_pg(self._target_pool(op), op.oid)
        primary = m.pg_primary(pgid)
        if primary is None:
            return None
        addr = m.get_addr(primary)
        if addr is None:
            return None
        op.attempts += 1
        self.msgr.send_message(
            MOSDOp(tid=op.tid, pgid=str(pgid), oid=op.oid, ops=op.ops,
                   epoch=m.epoch, snapc=op.snapc, snapid=op.snapid),
            f"osd.{primary}", tuple(addr))
        return primary

    # -- map change: resend everything pending (resend_mon_ops model) ------

    def _on_map(self, osdmap: OSDMap) -> None:
        with self._lock:
            pending = [op for op in self._ops.values() if op.reply is None]
        for op in pending:
            self._send(op)
        for hook in list(self.on_map_hooks):
            try:
                hook(osdmap)
            except Exception:
                self.log.error("on-map hook failed")

    # -- dispatch ----------------------------------------------------------

    def ms_dispatch(self, conn, msg: Message) -> bool:
        if isinstance(msg, MOSDOpReply):
            with self._lock:
                op = self._ops.get(msg.tid)
            if op is not None:
                op.reply = msg
                op.event.set()
            return True
        return False

    def ms_handle_reset(self, conn) -> None:
        # resend pending ops addressed to the dead peer on next map
        pass
