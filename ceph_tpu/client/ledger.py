"""DurabilityLedger: the client-side acked-write oracle.

A storage system's first contract is that an acknowledged write
survives a crash.  The ledger is how the chaos harness checks it the
Jepsen way: every payload a client ACTUALLY submitted is recorded
(with a digest) BEFORE the op goes out, promoted to "acked" when the
cluster acknowledges it, and after any number of crash-restart cycles
``verify`` asserts, per object:

  * the last ACKED payload is readable and bit-exact — a lost acked
    write is the one unforgivable outcome;
  * an object may instead hold a payload that was submitted but never
    acked (the crash ate the ack, not the write) — allowed, but only
    BIT-EXACT WHOLE: the read must equal exactly one recorded payload,
    so a torn/partially-applied transaction (bytes from two
    generations mixed) has no digest to match and fails loudly;
  * an acked delete stays deleted (no resurrection), and an object
    that was never acked into existence may be absent.

Bookkeeping assumes each object is mutated by one logical client
stream at a time (concurrent streams use disjoint oids — the chaos
harness's layout), matching the per-object ordering the cluster
itself guarantees.

The oracle covers EVERY front door, not just RADOS: `CephFSDoor` and
`RGWDoor` duck-type the IoCtx surface the ledger drives (write_full /
remove_object / read with RadosError errno semantics), so the same
write/delete/verify machinery crash-verifies acked CephFS metadata
mutations (file create + data write + size flush, unlink) and RGW
object puts/deletes over HTTP.
"""

from __future__ import annotations

import hashlib
import threading
import time

from .rados import RadosError

ETIMEDOUT = 110
ENOENT = 2

# marker for "object absent" outcomes (deletes) in the candidate sets
_ABSENT = "<absent>"


def _digest(payload: bytes) -> str:
    return hashlib.sha256(bytes(payload)).hexdigest()


class LedgerViolation(AssertionError):
    """A durability guarantee was broken (lost acked write, resurrected
    delete, or torn/partially-applied state)."""


def _flight_record(oid: str, detail: str, acked, candidates) -> None:
    """Feed the op-tracing flight recorder BEFORE the violation
    propagates: when armed (conf flight_recorder_dir, or a test
    fixture arming it directly) every registered daemon's in-flight +
    historic ops and pg log summaries are snapshotted — the 'deg:
    ACKED write lost' class of flake becomes a captured timeline
    instead of a rerun-and-hope.  Disarmed: one flag check.  Never
    raises; the violation stays the headline."""
    try:
        from ..utils import optracker
        optracker.flight_record(
            f"ledger-{oid}",
            extra={"oid": oid, "violation": detail,
                   "acked_digest": acked,
                   "candidate_digests": sorted(candidates or ())})
    except Exception:
        pass


class DurabilityLedger:
    def __init__(self):
        self._lock = threading.Lock()
        # oid -> digest of the last ACKED payload (_ABSENT = acked
        # delete); missing key = never acked into existence
        self._acked: dict[str, str] = {}
        # oid -> {digests submitted but not (yet) acked since the last
        # ack}: any of these MAY be on disk after a crash
        self._maybe: dict[str, set[str]] = {}
        self.acked_writes = 0
        self.acked_deletes = 0

    # -- bookkeeping -------------------------------------------------------

    def note_submit(self, oid: str, payload: bytes) -> None:
        """About to submit a write of `payload`: whatever happens next
        (ack, timeout, crash), this payload may reach disk."""
        with self._lock:
            self._maybe.setdefault(oid, set()).add(_digest(payload))

    def note_ack(self, oid: str, payload: bytes) -> None:
        """The cluster acked the write: from now on losing it is data
        loss.  Earlier unacked candidates are superseded."""
        with self._lock:
            self._acked[oid] = _digest(payload)
            self._maybe.pop(oid, None)
            self.acked_writes += 1

    def note_delete_submit(self, oid: str) -> None:
        with self._lock:
            self._maybe.setdefault(oid, set()).add(_ABSENT)

    def note_delete_ack(self, oid: str) -> None:
        with self._lock:
            self._acked[oid] = _ABSENT
            self._maybe.pop(oid, None)
            self.acked_deletes += 1

    def oids(self) -> list[str]:
        with self._lock:
            return sorted(set(self._acked) | set(self._maybe))

    # -- driving convenience ----------------------------------------------

    def write(self, io, oid: str, payload: bytes,
              retry_window: float = 90.0, on_retry=None) -> bool:
        """write_full with ledger bookkeeping: submit is recorded
        first, timeouts are retried (the resend may commit the FIRST
        attempt — same payload, so one candidate digest covers both),
        and only a real cluster ack promotes to acked.  Returns True
        on ack, False when the window closed with the payload still
        only a candidate."""
        self.note_submit(oid, payload)
        end = time.time() + retry_window
        while True:
            try:
                io.write_full(oid, payload)
            except RadosError as e:
                if e.errno != ETIMEDOUT:
                    raise
                if time.time() > end:
                    return False
                if on_retry is not None:
                    on_retry()
                continue
            self.note_ack(oid, payload)
            return True

    def delete(self, io, oid: str, retry_window: float = 90.0,
               on_retry=None) -> bool:
        self.note_delete_submit(oid)
        end = time.time() + retry_window
        while True:
            try:
                io.remove_object(oid)
            except RadosError as e:
                if e.errno == ENOENT:
                    pass       # an earlier timed-out attempt committed
                elif e.errno != ETIMEDOUT:
                    raise
                elif time.time() > end:
                    return False
                else:
                    if on_retry is not None:
                        on_retry()
                    continue
            self.note_delete_ack(oid)
            return True

    # -- the oracle --------------------------------------------------------

    def expected(self, oid: str) -> tuple[str | None, set[str]]:
        """(acked outcome or None, candidate outcomes) for an oid."""
        with self._lock:
            return self._acked.get(oid), set(self._maybe.get(oid, ()))

    def verify(self, io, retry_window: float = 60.0,
               on_retry=None) -> dict:
        """Assert every recorded object against the live cluster.
        Retries ETIMEDOUT reads inside the window (the cluster may
        still be re-peering after a restart); any durability violation
        raises LedgerViolation naming the oid and what was found."""
        checked = bitexact = unacked_seen = absent = 0
        for oid in self.oids():
            acked, maybe = self.expected(oid)
            end = time.time() + retry_window
            while True:
                got: str | None
                try:
                    got = _digest(io.read(oid))
                except RadosError as e:
                    if e.errno == ENOENT:
                        got = _ABSENT
                    elif e.errno == ETIMEDOUT and time.time() < end:
                        if on_retry is not None:
                            on_retry()
                        continue
                    else:
                        _flight_record(
                            oid, f"read errno {e.errno} past window",
                            acked, maybe)
                        raise LedgerViolation(
                            f"{oid}: read failed with errno {e.errno} "
                            f"past the retry window") from e
                break
            checked += 1
            if got == acked:
                bitexact += 1
                if got == _ABSENT:
                    absent += 1
                continue
            if got in maybe:
                # a submitted-but-unacked payload landed whole, or an
                # unacked delete took effect: atomic, allowed
                unacked_seen += 1
                if got == _ABSENT:
                    absent += 1
                continue
            if acked is None and got == _ABSENT:
                absent += 1    # never acked into existence: absence ok
                continue
            if got == _ABSENT:
                _flight_record(oid, "ACKED write lost (absent)",
                               acked, maybe)
                raise LedgerViolation(
                    f"{oid}: ACKED write lost (object absent, expected "
                    f"digest {acked})")
            _flight_record(oid, f"torn/resurrected state: read {got}",
                           acked, maybe)
            raise LedgerViolation(
                f"{oid}: read digest {got} matches no recorded payload "
                f"(acked {acked}, candidates {sorted(maybe)}) — torn "
                f"or resurrected state")
        return {"checked": checked, "bitexact_acked": bitexact,
                "unacked_candidates_seen": unacked_seen,
                "absent": absent, "acked_writes": self.acked_writes,
                "acked_deletes": self.acked_deletes}


class CephFSDoor:
    """CephFS front door for the ledger: each oid is a file under
    `root`, so a ledger write exercises the MDS metadata mutation
    chain (dentry+inode create, striper data write, size flush) and
    verify proves acked mutations survive crash-restart cycles."""

    def __init__(self, fs, root: str = "/ledger"):
        self.fs = fs
        self.root = root.rstrip("/") or "/ledger"
        # per-path serialization standing in for CephFS file
        # capabilities: a real MDS revokes Fr from readers while a
        # writer holds Fw, so open-truncate-write is never observable
        # half-done — without this a concurrent read can see the
        # truncated-empty window and the stale-read oracle (rightly)
        # flags bytes belonging to no write
        self._mu = threading.Lock()
        self._paths: dict[str, threading.Lock] = {}
        try:
            fs.mkdirs(self.root)
        except RadosError as e:
            if e.errno != 17:          # EEXIST is fine; fail fast on
                raise                  # real MDS/store errors

    def _path(self, oid: str) -> str:
        return f"{self.root}/{oid}"

    def _cap(self, oid: str) -> threading.Lock:
        with self._mu:
            return self._paths.setdefault(oid, threading.Lock())

    def write_full(self, oid: str, payload: bytes) -> None:
        with self._cap(oid):
            with self.fs.open(self._path(oid), "w") as f:
                f.write(bytes(payload))

    def remove_object(self, oid: str) -> None:
        with self._cap(oid):
            self.fs.unlink(self._path(oid))  # FsError IS a RadosError

    def read(self, oid: str) -> bytes:
        with self._cap(oid):
            with self.fs.open(self._path(oid), "r") as f:
                return f.read()


class RGWDoor:
    """RGW front door for the ledger: oids are S3 object keys in one
    bucket, driven over real HTTP — an acked PUT/DELETE is promoted
    exactly when the gateway's 2xx lands, and verify reads via GET.
    Transport failures and 5xx map to ETIMEDOUT (retryable), 404 to
    ENOENT, anything else to EIO."""

    def __init__(self, base_url: str, bucket: str = "ledger",
                 timeout: float = 30.0, headers: dict | None = None):
        self.base = base_url.rstrip("/")
        self.bucket = bucket
        self.timeout = timeout
        self.headers = dict(headers or {})
        try:
            self._req("PUT", f"/{bucket}")
        except RadosError as e:
            if e.errno not in (17,):   # EEXIST is fine
                raise

    def _req(self, method: str, path: str,
             data: bytes | None = None) -> bytes:
        import urllib.error
        import urllib.request
        req = urllib.request.Request(
            f"{self.base}{path}", data=data, method=method,
            headers=self.headers)
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                raise RadosError(ENOENT, f"{method} {path}: 404") \
                    from e
            if e.code == 409:
                raise RadosError(17, f"{method} {path}: 409") from e
            if e.code >= 500:
                raise RadosError(ETIMEDOUT,
                                 f"{method} {path}: {e.code}") from e
            raise RadosError(5, f"{method} {path}: {e.code}") from e
        except OSError as e:           # refused/reset/timeout
            raise RadosError(ETIMEDOUT, f"{method} {path}: {e}") from e

    def write_full(self, oid: str, payload: bytes) -> None:
        self._req("PUT", f"/{self.bucket}/{oid}", bytes(payload))

    def remove_object(self, oid: str) -> None:
        self._req("DELETE", f"/{self.bucket}/{oid}")

    def read(self, oid: str) -> bytes:
        return self._req("GET", f"/{self.bucket}/{oid}")


class SwiftDoor:
    """Swift front door for the ledger: the same gateway namespace as
    :class:`RGWDoor`, spoken as TempAuth'd Swift v1 — the token is
    minted at ``/auth/v1.0`` from the account credentials and carried
    as ``X-Auth-Token`` on every container/object op (re-minted once
    on a 401, covering token expiry).  Errno mapping matches RGWDoor
    so the same ledger/fault drills drive both dialects."""

    def __init__(self, base_url: str, container: str = "ledger",
                 access_key: str = "", secret_key: str = "",
                 timeout: float = 30.0):
        self.base = base_url.rstrip("/")
        self.container = container
        self.access_key = access_key
        self.secret_key = secret_key
        self.timeout = timeout
        self._token = ""
        self._acct = f"AUTH_{access_key or 'anon'}"
        try:
            self._req("PUT", f"/v1/{self._acct}/{container}")
        except RadosError as e:
            if e.errno != 17:          # 202 re-PUT never errors; only
                raise                  # real failures propagate

    def _authenticate(self) -> None:
        import urllib.request
        req = urllib.request.Request(
            f"{self.base}/auth/v1.0", method="GET",
            headers={"X-Auth-User": f"{self.access_key}:swift",
                     "X-Auth-Key": self.secret_key})
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            self._token = r.headers.get("X-Auth-Token", "")

    def _req(self, method: str, path: str,
             data: bytes | None = None, _retry: bool = True) -> bytes:
        import urllib.error
        import urllib.request
        try:
            if not self._token:
                self._authenticate()
            req = urllib.request.Request(
                f"{self.base}{path}", data=data, method=method,
                headers={"X-Auth-Token": self._token})
            with urllib.request.urlopen(req,
                                        timeout=self.timeout) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            if e.code == 401 and _retry:
                self._token = ""       # expired: re-mint once
                return self._req(method, path, data, _retry=False)
            if e.code == 404:
                raise RadosError(ENOENT, f"{method} {path}: 404") \
                    from e
            if e.code == 409:
                raise RadosError(17, f"{method} {path}: 409") from e
            if e.code >= 500:
                raise RadosError(ETIMEDOUT,
                                 f"{method} {path}: {e.code}") from e
            raise RadosError(5, f"{method} {path}: {e.code}") from e
        except OSError as e:           # refused/reset/timeout
            raise RadosError(ETIMEDOUT, f"{method} {path}: {e}") from e

    def _opath(self, oid: str) -> str:
        return f"/v1/{self._acct}/{self.container}/{oid}"

    def write_full(self, oid: str, payload: bytes) -> None:
        self._req("PUT", self._opath(oid), bytes(payload))

    def remove_object(self, oid: str) -> None:
        self._req("DELETE", self._opath(oid))

    def read(self, oid: str) -> bytes:
        return self._req("GET", self._opath(oid))


class TwoZoneLedger(DurabilityLedger):
    """The two-zone durability oracle: acks are recorded at the
    PRIMARY zone's door (the only door clients write), and
    :meth:`verify_zones` proves the multisite promise on top of the
    single-zone oracle:

      * the primary passes the base :meth:`verify` (acked state
        bit-exact, no torn bytes, deletes deleted);
      * the REPLICA zone eventually converges to exactly the
        primary's surviving state per object — async replication is
        allowed lag, never divergence (a candidate payload that
        landed at the primary without an ack replicates too, so the
        equality is against what the primary actually holds);
      * an object whose delete was acked at the primary never
        RESURRECTS at either zone, no matter how the partition /
        crash schedule interleaved with full/incremental sync.
    """

    def __init__(self, primary, replica):
        super().__init__()
        self.primary = primary
        self.replica = replica

    # writes/deletes enter at the primary zone only

    def write_primary(self, oid: str, payload: bytes,
                      retry_window: float = 90.0, on_retry=None) -> bool:
        return self.write(self.primary, oid, payload,
                          retry_window=retry_window, on_retry=on_retry)

    def delete_primary(self, oid: str, retry_window: float = 90.0,
                       on_retry=None) -> bool:
        return self.delete(self.primary, oid,
                           retry_window=retry_window, on_retry=on_retry)

    def _read_state(self, door, oid: str, retry_window: float,
                    on_retry) -> str:
        end = time.time() + retry_window
        while True:
            try:
                return _digest(door.read(oid))
            except RadosError as e:
                if e.errno == ENOENT:
                    return _ABSENT
                if e.errno == ETIMEDOUT and time.time() < end:
                    if on_retry is not None:
                        on_retry()
                    continue
                raise LedgerViolation(
                    f"{oid}: zone read failed with errno {e.errno} "
                    f"past the retry window") from e

    def verify_zones(self, retry_window: float = 60.0,
                     convergence_window: float = 60.0,
                     on_retry=None) -> dict:
        out = {"primary": self.verify(self.primary,
                                      retry_window=retry_window,
                                      on_retry=on_retry)}
        converged = 0
        for oid in self.oids():
            want = self._read_state(self.primary, oid, retry_window,
                                    on_retry)
            end = time.time() + convergence_window
            while True:
                got = self._read_state(self.replica, oid,
                                       retry_window, on_retry)
                if got == want:
                    break
                if time.time() > end:
                    acked, maybe = self.expected(oid)
                    _flight_record(
                        oid, f"replica never converged: primary "
                             f"{want}, replica {got}", acked, maybe)
                    raise LedgerViolation(
                        f"{oid}: replica zone never converged "
                        f"(primary {want}, replica {got} after "
                        f"{convergence_window}s)")
                if on_retry is not None:
                    on_retry()
                time.sleep(0.1)
            converged += 1
        # no-resurrection sweep: an ACKED delete must hold at BOTH
        # zones — a full sync racing the tombstone must not have
        # copied the object back
        resurrect_checked = 0
        for oid in self.oids():
            acked, _maybe = self.expected(oid)
            if acked != _ABSENT:
                continue
            for zone, door in (("primary", self.primary),
                               ("replica", self.replica)):
                got = self._read_state(door, oid, retry_window,
                                       on_retry)
                if got != _ABSENT:
                    _flight_record(oid, f"delete resurrected at "
                                        f"{zone}: {got}", acked, ())
                    raise LedgerViolation(
                        f"{oid}: acked delete RESURRECTED at the "
                        f"{zone} zone (read digest {got})")
            resurrect_checked += 1
        out["replica_converged"] = converged
        out["deletes_held_both_zones"] = resurrect_checked
        return out
