"""Client tier: Objecter + librados-style API (osdc/ + librados/ analog)."""

from .rados import Rados, IoCtx, RadosError

__all__ = ["Rados", "IoCtx", "RadosError"]
