"""Client tier: Objecter + librados-style API (osdc/ + librados/ analog)."""

from .rados import Rados, IoCtx, RadosError
from .ledger import (CephFSDoor, DurabilityLedger, LedgerViolation,
                     RGWDoor, SwiftDoor, TwoZoneLedger)

__all__ = ["Rados", "IoCtx", "RadosError", "DurabilityLedger",
           "LedgerViolation", "CephFSDoor", "RGWDoor", "SwiftDoor",
           "TwoZoneLedger"]
