"""GF(2^8) arithmetic and erasure-code matrix constructions (host side).

This is the mathematical core behind every Reed-Solomon / Cauchy erasure
code technique in the framework.  All arithmetic is over GF(2^8) with the
primitive polynomial x^8 + x^4 + x^3 + x^2 + 1 (0x11d), the polynomial used
by both jerasure/gf-complete (w=8) and Intel ISA-L, so chunk bytes produced
here are compatible with the reference plugins' techniques
(reference: /root/reference/src/erasure-code/jerasure/ErasureCodeJerasure.cc,
/root/reference/src/erasure-code/isa/ErasureCodeIsa.cc).

The TPU twist: GF(2^8) multiplication by a *constant* is linear over GF(2)
on the 8 bits of a byte, so any (m x k) generator matrix of bytes expands to
an (8m x 8k) 0/1 matrix and the whole encode becomes a plain integer matmul
followed by mod-2 — which is exactly what a TPU MXU is good at.  The
expansion helpers at the bottom of this file produce those bit-matrices;
`ceph_tpu.ops.ec_kernels` turns them into jitted device code.
"""

from __future__ import annotations

import functools

import numpy as np

GF_POLY = 0x11D  # x^8+x^4+x^3+x^2+1, primitive; generator alpha=2
GF_ORDER = 256


def _build_tables() -> tuple[np.ndarray, np.ndarray]:
    """Antilog (exp) and log tables for alpha=2 under poly 0x11d."""
    exp = np.zeros(512, dtype=np.uint8)
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= GF_POLY
    exp[255:510] = exp[0:255]  # wraparound so exp[a+b] works without mod
    return exp, log


GF_EXP, GF_LOG = _build_tables()


@functools.lru_cache(maxsize=1)
def mul_table() -> np.ndarray:
    """Full 256x256 multiplication table (64 KiB), for vectorized gf ops."""
    a = np.arange(256, dtype=np.int32)
    la = GF_LOG[a][:, None]
    lb = GF_LOG[a][None, :]
    t = GF_EXP[(la + lb) % 255].astype(np.uint8)
    t[0, :] = 0
    t[:, 0] = 0
    return t


def gf_mul(a, b):
    """Element-wise GF(2^8) multiply; accepts scalars or uint8 arrays."""
    return mul_table()[np.asarray(a, dtype=np.uint8), np.asarray(b, dtype=np.uint8)]


def gf_inv(a):
    a = np.asarray(a, dtype=np.uint8)
    if np.any(a == 0):
        raise ZeroDivisionError("gf_inv(0)")
    return GF_EXP[(255 - GF_LOG[a]) % 255]


def gf_div(a, b):
    return gf_mul(a, gf_inv(b))


def gf_pow(a: int, n: int) -> int:
    if n == 0:
        return 1
    if a == 0:
        return 0
    return int(GF_EXP[(int(GF_LOG[a]) * n) % 255])


def gf_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Matrix product over GF(2^8): XOR-accumulate of gf_mul."""
    a = np.asarray(a, dtype=np.uint8)
    b = np.asarray(b, dtype=np.uint8)
    prod = mul_table()[a[:, :, None], b[None, :, :]]  # (r, n, c)
    return np.bitwise_xor.reduce(prod, axis=1)


def gf_matvec(a: np.ndarray, v: np.ndarray) -> np.ndarray:
    return gf_matmul(a, v.reshape(-1, 1)).reshape(-1)


def gf_mat_inv(a: np.ndarray) -> np.ndarray:
    """Gauss-Jordan inverse over GF(2^8). Raises if singular."""
    a = np.array(a, dtype=np.uint8)
    n = a.shape[0]
    if a.shape != (n, n):
        raise ValueError("square matrix required")
    aug = np.concatenate([a, np.eye(n, dtype=np.uint8)], axis=1)
    for col in range(n):
        pivot = None
        for row in range(col, n):
            if aug[row, col]:
                pivot = row
                break
        if pivot is None:
            raise np.linalg.LinAlgError("singular matrix over GF(2^8)")
        if pivot != col:
            aug[[col, pivot]] = aug[[pivot, col]]
        aug[col] = gf_mul(aug[col], gf_inv(aug[col, col]))
        for row in range(n):
            if row != col and aug[row, col]:
                aug[row] ^= gf_mul(aug[row, col], aug[col])
    return aug[:, n:]


# ---------------------------------------------------------------------------
# Generator matrix constructions
#
# Each returns the m x k "coding rows" (the implicit identity on top makes
# the code systematic).  Constructions follow the published algorithms the
# reference's vendored C libraries implement (Plank's jerasure papers,
# ISA-L's ec_base), so that chunks are technique-compatible.
# ---------------------------------------------------------------------------


def extended_vandermonde(rows: int, cols: int) -> np.ndarray:
    """Extended Vandermonde matrix per Plank's RS tutorial correction.

    Row 0 is e_0, row rows-1 is e_{cols-1}, middle rows i are
    [i^0, i^1, ..., i^{cols-1}] over GF(2^8).
    """
    v = np.zeros((rows, cols), dtype=np.uint8)
    v[0, 0] = 1
    for i in range(1, rows - 1):
        acc = 1
        for j in range(cols):
            v[i, j] = acc
            acc = int(gf_mul(acc, i))
    v[rows - 1, cols - 1] = 1
    return v


def reed_sol_van_matrix(k: int, m: int) -> np.ndarray:
    """Systematic RS generator, jerasure `reed_sol_van` technique (w=8).

    Builds the (k+m) x k extended Vandermonde matrix and column-reduces it
    so the top k x k block is the identity; the bottom m rows are the
    coding matrix (row 0 always all-ones).  Same elimination order as the
    published algorithm so outputs match the reference technique
    (reference wrapper: ErasureCodeJerasureReedSolomonVandermonde::prepare,
    /root/reference/src/erasure-code/jerasure/ErasureCodeJerasure.cc:215).
    """
    rows = k + m
    if rows > GF_ORDER:
        raise ValueError("k+m must be <= 256 for w=8")
    v = extended_vandermonde(rows, k)
    # Column-reduce top square to identity (elementary column operations
    # preserve the code's systematic property).
    for i in range(k):
        if v[i, i] == 0:
            for j in range(i + 1, k):
                if v[i, j]:
                    v[:, [i, j]] = v[:, [j, i]]
                    break
            else:
                raise np.linalg.LinAlgError("vandermonde reduction failed")
        if v[i, i] != 1:
            v[:, i] = gf_mul(v[:, i], gf_inv(v[i, i]))
        for j in range(k):
            if j != i and v[i, j]:
                v[:, j] ^= gf_mul(v[i, j], v[:, i])
    assert np.array_equal(v[:k], np.eye(k, dtype=np.uint8))
    # Normalize so the first coding row is all ones (pure-XOR parity), per
    # the published algorithm: scale column j by 1/v[k][j], then rescale
    # identity row j to restore the 1 on the diagonal.  This yields an
    # equivalent generalized-RS code with cheaper first parity.
    if m > 0:
        for j in range(k):
            d = int(v[k, j])
            if d == 0:
                raise np.linalg.LinAlgError("non-MDS vandermonde reduction")
            if d != 1:
                inv = gf_inv(d)
                v[:, j] = gf_mul(v[:, j], inv)
                v[j, j] = 1
    assert np.array_equal(v[:k], np.eye(k, dtype=np.uint8))
    assert m == 0 or np.all(v[k] == 1)
    return v[k:]


def reed_sol_r6_matrix(k: int) -> np.ndarray:
    """RAID-6 generator (jerasure `reed_sol_r6_op`): P = xor, Q = sum 2^j d_j."""
    coding = np.zeros((2, k), dtype=np.uint8)
    coding[0, :] = 1
    for j in range(k):
        coding[1, j] = gf_pow(2, j)
    return coding


def isa_rs_matrix(k: int, m: int) -> np.ndarray:
    """ISA-L `reed_sol_van` generator (gf_gen_rs_matrix semantics).

    Coding row r uses powers of g_r = 2^r: entry j = g_r^j.  Matches the
    matrix the reference isa plugin feeds to ec_encode_data
    (/root/reference/src/erasure-code/isa/ErasureCodeIsa.cc:553 region).
    Note: like ISA-L, this is only guaranteed MDS for small k+m.
    """
    coding = np.zeros((m, k), dtype=np.uint8)
    gen = 1
    for r in range(m):
        p = 1
        for j in range(k):
            coding[r, j] = p
            p = int(gf_mul(p, gen))
        gen = int(gf_mul(gen, 2))
    return coding


def isa_cauchy_matrix(k: int, m: int) -> np.ndarray:
    """ISA-L `cauchy` generator (gf_gen_cauchy1_matrix semantics)."""
    coding = np.zeros((m, k), dtype=np.uint8)
    for r in range(m):
        i = k + r
        for j in range(k):
            coding[r, j] = gf_inv(i ^ j)
    return coding


def cauchy_orig_matrix(k: int, m: int) -> np.ndarray:
    """jerasure `cauchy_orig`: M[i][j] = 1 / (i xor (m+j)) over GF(2^8)."""
    if k + m > GF_ORDER:
        raise ValueError("k+m must be <= 256 for w=8")
    coding = np.zeros((m, k), dtype=np.uint8)
    for i in range(m):
        for j in range(k):
            coding[i, j] = gf_inv(i ^ (m + j))
    return coding


def bit_weight(e: int, w: int = 8) -> int:
    """Number of ones in the w x w GF(2) bit-matrix of multiply-by-e.

    This is jerasure's cauchy_n_ones cost metric: the XOR count of the
    bit-matrix schedule for multiplying a word by constant e.
    """
    return int(byte_bitmatrix(e, w).sum())


def cauchy_good_matrix(k: int, m: int) -> np.ndarray:
    """jerasure `cauchy_good`: cauchy_orig improved to minimize XOR count.

    Normalizes column j by M[0][j] (first row becomes all ones), then for
    each later row picks the divisor among its elements that minimizes the
    total bit-matrix ones of the row.
    """
    mtx = cauchy_orig_matrix(k, m)
    for j in range(k):
        if mtx[0, j] != 1:
            mtx[:, j] = gf_div(mtx[:, j], mtx[0, j])
    for i in range(1, m):
        best_div, best_cost = 1, sum(bit_weight(int(e)) for e in mtx[i])
        for d in mtx[i]:
            d = int(d)
            if d in (0, 1):
                continue
            cost = sum(bit_weight(int(e)) for e in gf_div(mtx[i], d))
            if cost < best_cost:
                best_div, best_cost = d, cost
        if best_div != 1:
            mtx[i] = gf_div(mtx[i], best_div)
    return mtx


def systematic_generator(coding: np.ndarray, k: int) -> np.ndarray:
    """Stack identity over the coding rows: full (k+m) x k generator."""
    return np.concatenate([np.eye(k, dtype=np.uint8), coding], axis=0)


def decode_matrix(generator: np.ndarray, k: int, present: list[int]) -> np.ndarray:
    """Rows that rebuild the k data chunks from `present` chunk indices.

    Select k generator rows (one per surviving chunk), invert over GF(2^8);
    row i of the result reconstructs data chunk i as a combination of the
    surviving chunks, in the order given by `present`.
    """
    if len(present) != k:
        raise ValueError(f"need exactly k={k} present chunks, got {len(present)}")
    sub = generator[np.asarray(present, dtype=np.int64)]
    return gf_mat_inv(sub)


# ---------------------------------------------------------------------------
# GF(2) bit-matrix expansion: the bridge to the TPU MXU
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _byte_bitmatrix_cached(e: int, w: int) -> bytes:
    cols = []
    x = e
    for _t in range(w):
        cols.append([(x >> b) & 1 for b in range(w)])
        x = int(gf_mul(x, 2)) if w == 8 else _gfw_mul2(x, w)
    # cols[t][b] = bit b of e * alpha^t ; we want M[b][t]
    m = np.array(cols, dtype=np.uint8).T
    return m.tobytes()


def _gfw_mul2(x: int, w: int) -> int:
    polys = {4: 0x13, 8: 0x11D, 16: 0x1100B, 32: 0x100400007}
    x <<= 1
    if x >> w:
        x ^= polys[w]
    return x


def byte_bitmatrix(e: int, w: int = 8) -> np.ndarray:
    """w x w GF(2) matrix M with bits(e*x) = M @ bits(x) mod 2.

    Column t holds the bits of e * alpha^t (alpha = 2); for t < w that
    equals e * (1<<t), i.e. the image of basis bit t.
    """
    return np.frombuffer(_byte_bitmatrix_cached(int(e), w), dtype=np.uint8).reshape(w, w)


def expand_bitmatrix(mtx: np.ndarray, w: int = 8) -> np.ndarray:
    """Expand an (r x c) GF(2^w) matrix to an (r*w x c*w) GF(2) matrix.

    Same block layout as jerasure_matrix_to_bitmatrix: block (i, j) is the
    w x w multiply-by-mtx[i,j] matrix, so for packetized data
    out_packet[i*w + b] = xor over (j, t) with bit set of in_packet[j*w + t].
    """
    r, c = mtx.shape
    out = np.zeros((r * w, c * w), dtype=np.uint8)
    for i in range(r):
        for j in range(c):
            out[i * w:(i + 1) * w, j * w:(j + 1) * w] = byte_bitmatrix(int(mtx[i, j]), w)
    return out


# ---------------------------------------------------------------------------
# numpy reference encode/decode (ground truth for kernels and native code)
# ---------------------------------------------------------------------------


def encode_np(coding: np.ndarray, data: np.ndarray) -> np.ndarray:
    """data: (k, L) uint8 -> parity (m, L) uint8, pure numpy (slow, exact)."""
    m, k = coding.shape
    assert data.shape[0] == k
    out = np.zeros((m, data.shape[1]), dtype=np.uint8)
    tbl = mul_table()
    for i in range(m):
        acc = np.zeros(data.shape[1], dtype=np.uint8)
        for j in range(k):
            acc ^= tbl[coding[i, j]][data[j]]
        out[i] = acc
    return out


def bitmatrix_encode_np(bitmatrix: np.ndarray, data: np.ndarray,
                        w: int, packetsize: int) -> np.ndarray:
    """Packetized GF(2) schedule encode (jerasure bitmatrix semantics).

    data: (k, L) uint8 with L % (w*packetsize) == 0.  Chunk j is a sequence
    of super-blocks of w packets of `packetsize` bytes; coding chunk i's
    packet b is the XOR of all data packets (j, t) whose bit is set in
    bitmatrix[i*w+b, j*w+t].
    """
    mw, kw = bitmatrix.shape
    m, k = mw // w, kw // w
    assert data.shape[0] == k
    L = data.shape[1]
    assert L % (w * packetsize) == 0, (L, w, packetsize)
    nblk = L // (w * packetsize)
    d = data.reshape(k, nblk, w, packetsize)
    out = np.zeros((m, nblk, w, packetsize), dtype=np.uint8)
    for i in range(m):
        for b in range(w):
            row = bitmatrix[i * w + b]
            acc = np.zeros((nblk, packetsize), dtype=np.uint8)
            for j in range(k):
                for t in range(w):
                    if row[j * w + t]:
                        acc ^= d[j, :, t, :]
            out[i, :, b, :] = acc
    return out.reshape(m, L)


# ---------------------------------------------------------------------------
# Minimal-density bit-matrix techniques (m=2 RAID-6 family)
#
# These are NATIVE GF(2) bit-matrices, not expansions of GF(2^w) byte
# matrices (reference: jerasure's liberation.c constructions used by
# erasure-code/jerasure/ErasureCodeJerasure.h:176-259).  Layout matches
# expand_bitmatrix: parity chunk i's packet b = XOR of data packets
# (j, t) with bits[i*w + b, j*w + t] set.
# ---------------------------------------------------------------------------


def _is_prime(n: int) -> bool:
    if n < 2:
        return False
    for p in range(2, int(n ** 0.5) + 1):
        if n % p == 0:
            return False
    return True


def liberation_bitmatrix(k: int, w: int) -> np.ndarray:
    """Liberation codes (Plank 2008): w prime, k <= w, m = 2.

    Parity 0 is the XOR of corresponding bits (identity blocks);
    parity 1's block for data column j is the identity rotated by j
    with one extra "bonus" bit for j > 0 — the minimal-density
    construction of jerasure's liberation_coding_bitmatrix.
    """
    if not _is_prime(w):
        raise ValueError(f"liberation requires prime w, got {w}")
    if k > w:
        raise ValueError(f"liberation requires k <= w ({k} > {w})")
    bits = np.zeros((2 * w, k * w), dtype=np.uint8)
    for i in range(w):
        for j in range(k):
            bits[i, j * w + i] = 1
    for j in range(k):
        for i in range(w):
            bits[w + i, j * w + (j + i) % w] = 1
        if j > 0:
            i = (j * ((w - 1) // 2)) % w
            bits[w + i, j * w + (i + j - 1) % w] = 1
    return bits


def blaum_roth_bitmatrix(k: int, w: int) -> np.ndarray:
    """Blaum-Roth codes: w + 1 prime, k <= w, m = 2.

    Parity 1's block for data column j is multiplication by x^j in the
    ring F2[x]/M_p(x), M_p = (x^p - 1)/(x - 1), p = w + 1: basis
    x^t -> x^((j+t) mod p), where x^w reduces to the all-ones vector.
    """
    p = w + 1
    if not _is_prime(p):
        raise ValueError(f"blaum_roth requires w+1 prime, got w={w}")
    if k > w:
        raise ValueError(f"blaum_roth requires k <= w ({k} > {w})")
    bits = np.zeros((2 * w, k * w), dtype=np.uint8)
    for i in range(w):
        for j in range(k):
            bits[i, j * w + i] = 1
    for j in range(k):
        for t in range(w):
            s = (j + t) % p
            if s == w:
                bits[w: 2 * w, j * w + t] = 1
            else:
                bits[w + s, j * w + t] = 1
    return bits


def liber8tion_bitmatrix(k: int) -> np.ndarray:
    """liber8tion slot: w = 8, m = 2, k <= 8.

    DIVERGENCE NOTE: the reference's liber8tion matrices are a table
    from Plank's paper (jerasure liber8tion.c), which is not available
    in this environment; this uses the multiply-by-alpha^j GF(2^8)
    bit-matrix (an MDS m=2 code with the same geometry).  On-disk
    parity bytes therefore differ from upstream jerasure's liber8tion.
    """
    if k > 8:
        raise ValueError(f"liber8tion requires k <= 8, got {k}")
    mtx = np.zeros((2, k), dtype=np.uint8)
    mtx[0, :] = 1
    for j in range(k):
        mtx[1, j] = gf_pow(2, j)
    return expand_bitmatrix(mtx, 8)


def gf2_inv(mat: np.ndarray) -> np.ndarray:
    """Invert a square 0/1 matrix over GF(2) (Gaussian elimination)."""
    n = mat.shape[0]
    a = (mat.astype(np.uint8) & 1).copy()
    inv = np.eye(n, dtype=np.uint8)
    for col in range(n):
        piv = None
        for r in range(col, n):
            if a[r, col]:
                piv = r
                break
        if piv is None:
            raise ValueError("singular GF(2) matrix")
        if piv != col:
            a[[col, piv]] = a[[piv, col]]
            inv[[col, piv]] = inv[[piv, col]]
        rows = np.nonzero(a[:, col])[0]
        rows = rows[rows != col]
        a[rows] ^= a[col]
        inv[rows] ^= inv[col]
    return inv


def bitmatrix_decode_rows(gen_bits: np.ndarray, k: int, w: int,
                          want: list, present: list) -> np.ndarray:
    """GF(2) decode planner for native bit-matrix codes.

    gen_bits: ((k+m)*w, k*w) systematic generator (identity on top).
    Returns (len(want)*w, len(present)*w) bits mapping the stacked
    surviving chunks' packets to the wanted chunks' packets.
    """
    assert len(present) >= k
    sel = np.vstack([gen_bits[c * w:(c + 1) * w] for c in present[:k]])
    inv = gf2_inv(sel)
    out_rows = []
    for c in want:
        rows = gen_bits[c * w:(c + 1) * w]
        out_rows.append((rows @ inv) & 1)
    out = np.vstack(out_rows).astype(np.uint8)
    # columns beyond the first k present chunks are unused
    if len(present) > k:
        pad = np.zeros((out.shape[0], (len(present) - k) * w),
                       dtype=np.uint8)
        out = np.hstack([out, pad])
    return out
