"""JAX/XLA device kernels: GF(2^8) erasure coding + CRC32C as matmuls.

The TPU-first formulation (this is the north-star kernel of the whole
framework, replacing the reference's ISA-L x86 assembly and gf-complete
SIMD paths, /root/reference/src/erasure-code/isa/isa-l/erasure_code/):

  * GF(2^8) multiply-by-constant is GF(2)-linear on a byte's 8 bits, so an
    (m x k) generator of bytes becomes an (8m x 8k) 0/1 matrix and encode
    is    parity_bits = (G_bits @ data_bits) mod 2
    — an int8 matmul on the MXU followed by a parity extraction.  Decode
    is the same matmul with an inverted matrix.  Bit-matrix techniques
    (cauchy, liberation) are *already* GF(2) matrices and map natively.

  * CRC32C is GF(2)-linear in the message, factored in two levels
    (ceph_tpu.ops.crc32c.block_crc_matrices): a shared 32x(8W) fold matmul
    per W-byte block plus per-position 32x32 combines.  Scrub checksums of
    every chunk ride the same device pass as the encode — "fused" in the
    sense that chunks are DMA'd once and XLA fuses unpack/fold.

Everything is traced once per (shape, matrix) and cached; shapes are
static, control flow is compile-time, no host sync inside the step.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import crc32c as crc_mod
from . import gf

# Accumulation dtype pairs: int8 inputs with int32 accumulation hits the
# MXU's integer path on TPU; bf16/f32 is a fallback knob for platforms
# where the int8 path is slow.
_COMPUTE_DTYPES = {
    "int8": (jnp.int8, jnp.int32),
    "bf16": (jnp.bfloat16, jnp.float32),
}

DEFAULT_COMPUTE = "int8"

_BIT_SHIFTS = tuple(1 << b for b in range(8))


def _unpack_bits(x: jnp.ndarray, in_dtype) -> jnp.ndarray:
    """(..., n, L) uint8 -> (..., n*8, L) bits, row index = n*8 + bit."""
    shifts = jnp.arange(8, dtype=jnp.uint8).reshape((1,) * (x.ndim - 1) + (8, 1))
    bits = (x[..., :, None, :] >> shifts) & jnp.uint8(1)
    shape = x.shape[:-2] + (x.shape[-2] * 8, x.shape[-1])
    return bits.reshape(shape).astype(in_dtype)


def _pack_bits(bits: jnp.ndarray) -> jnp.ndarray:
    """(..., n*8, L) {0,1} int32 -> (..., n, L) uint8."""
    shape = bits.shape[:-2] + (bits.shape[-2] // 8, 8, bits.shape[-1])
    b = bits.reshape(shape)
    weights = jnp.array(_BIT_SHIFTS, dtype=jnp.int32).reshape((1,) * (b.ndim - 3) + (1, 8, 1))
    return jnp.sum(b * weights, axis=-2).astype(jnp.uint8)


def _mod2(x: jnp.ndarray) -> jnp.ndarray:
    if jnp.issubdtype(x.dtype, jnp.integer):
        return (x & 1).astype(jnp.int32)
    # float accumulation: values are exact small integers
    return (x.astype(jnp.int32)) & 1


def gf2_matmul_bytes(g_bits: jnp.ndarray, data: jnp.ndarray,
                     compute: str = DEFAULT_COMPUTE) -> jnp.ndarray:
    """Apply a GF(2) bit-matrix to byte chunks.

    g_bits: (R, C) 0/1 (R, C multiples of 8), data: (..., C/8, L) uint8
    -> (..., R/8, L) uint8.  The contraction runs on the MXU.
    """
    in_dtype, acc_dtype = _COMPUTE_DTYPES[compute]
    bits = _unpack_bits(data, in_dtype)
    g = g_bits.astype(in_dtype)
    acc = jax.lax.dot_general(
        g, bits,
        dimension_numbers=(((1,), (bits.ndim - 2,)), ((), ())),
        preferred_element_type=acc_dtype,
    )
    # dot_general output: (R, ..., L) — move R after batch dims
    if bits.ndim > 2:
        perm = tuple(range(1, bits.ndim - 1)) + (0, bits.ndim - 1)
        acc = jnp.transpose(acc, perm)
    return _pack_bits(_mod2(acc))


def _k_packing(rows: int, cols: int, L: int) -> int:
    """Segments to pack per MXU column so the contraction fills K=128.

    The systolic array streams one K<=128 column per cycle; a GF(2^8)
    encode has K = 8k bits, so for small k most of each column is padding.
    Packing d independent L/d-byte segments block-diagonally multiplies
    per-cycle useful work by d (e.g. k=2 -> d=8, k=8 -> d=2).
    """
    d = max(1, 128 // cols)
    while d > 1 and (L % d or (rows * d) > 128):
        d -= 1
    return d


def gf2_matmul_bytes_packed(g_bits: jnp.ndarray, data: jnp.ndarray,
                            compute: str = DEFAULT_COMPUTE) -> jnp.ndarray:
    """Like gf2_matmul_bytes but block-diagonally packed to fill the MXU.

    data: (B, k, L) uint8 -> (B, m, L) uint8.
    """
    in_dtype, acc_dtype = _COMPUTE_DTYPES[compute]
    B, k, L = data.shape
    rows, cols = g_bits.shape
    m = rows // 8
    d = _k_packing(rows, cols, L)
    if d == 1:
        return gf2_matmul_bytes(g_bits, data, compute)
    Ld = L // d
    # block-diagonal packing = kron(I_d, g); jnp.kron keeps this
    # traceable (a sharded caller may feed a per-device generator
    # slice), and XLA constant-folds it for concrete matrices
    g = jnp.kron(jnp.eye(d, dtype=jnp.uint8),
                 jnp.asarray(g_bits, dtype=jnp.uint8)).astype(in_dtype)
    # segment b of the chunk axis -> block b of the packed contraction
    seg = data.reshape(B, k, d, Ld).transpose(0, 2, 1, 3)      # (B, d, k, Ld)
    bits = _unpack_bits(seg, in_dtype)                          # (B, d, 8k, Ld)
    bits = bits.reshape(B, d * cols, Ld)
    acc = jax.lax.dot_general(
        g, bits,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=acc_dtype,
    )                                                           # (dR, B, Ld)
    acc = jnp.transpose(acc, (1, 0, 2)).reshape(B, d, rows, Ld)
    packed = _pack_bits(_mod2(acc))                             # (B, d, m, Ld)
    return packed.transpose(0, 2, 1, 3).reshape(B, m, L)


@functools.lru_cache(maxsize=256)
def _encode_fn(g_bits_key: bytes, shape_key: tuple, compute: str):
    """Jitted (B, k, L) uint8 -> (B, m, L) uint8 parity."""
    rows, cols = shape_key
    g_bits = np.frombuffer(g_bits_key, dtype=np.uint8).reshape(rows, cols)
    g_const = jnp.asarray(g_bits)

    @jax.jit
    def run(data):
        return gf2_matmul_bytes_packed(g_const, data, compute)

    return run


def make_codec_fn(matrix: np.ndarray, w: int = 8,
                  compute: str = DEFAULT_COMPUTE):
    """Build a jitted chunk transform from a GF(2^w) byte matrix.

    matrix: (m, k) uint8 over GF(2^8) (or an already-expanded GF(2)
    bit-matrix when w == 1).  Returns fn(data: (B, k, L) or (k, L) uint8)
    -> same-rank parity array.
    """
    if w == 8:
        bits = gf.expand_bitmatrix(np.asarray(matrix, dtype=np.uint8), 8)
    elif w == 1:
        bits = np.asarray(matrix, dtype=np.uint8)
        assert bits.shape[0] % 8 == 0 and bits.shape[1] % 8 == 0
    else:
        raise ValueError(f"unsupported w={w}")
    fn = _encode_fn(bits.tobytes(), bits.shape, compute)

    def call(data):
        data = jnp.asarray(data, dtype=jnp.uint8)
        squeeze = data.ndim == 2
        if squeeze:
            data = data[None]
        out = fn(data)
        return out[0] if squeeze else out

    return call


# ---------------------------------------------------------------------------
# Packetized GF(2) transforms (jerasure bitmatrix techniques)
#
# Bit-matrix techniques (cauchy_*, liberation) lay a chunk out as
# super-blocks of w packets and XOR whole packets per the 0/1 schedule
# (reference semantics: jerasure_bitmatrix_encode packet loops).  A packet
# XOR is bitwise, so the whole schedule is ONE GF(2) matmul with the raw
# bitmatrix — no 8x expansion — batched over super-blocks on the MXU.
# ---------------------------------------------------------------------------


def gf2_packet_matmul(m_bits: jnp.ndarray, packets: jnp.ndarray,
                      compute: str = DEFAULT_COMPUTE) -> jnp.ndarray:
    """m_bits: (R, C) 0/1; packets: (..., C, P) uint8 -> (..., R, P) uint8.

    out[r] = XOR over c with m_bits[r, c] of packets[c]; bytes are 8
    independent GF(2) lanes, so unpack along the byte axis only.
    """
    in_dtype, acc_dtype = _COMPUTE_DTYPES[compute]
    lead = packets.shape[:-2]
    C, P = packets.shape[-2:]
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = ((packets[..., None] >> shifts) & jnp.uint8(1))
    bits = bits.reshape(lead + (C, P * 8)).astype(in_dtype)
    acc = jax.lax.dot_general(
        m_bits.astype(in_dtype), bits,
        dimension_numbers=(((1,), (bits.ndim - 2,)), ((), ())),
        preferred_element_type=acc_dtype,
    )
    if bits.ndim > 2:
        perm = tuple(range(1, bits.ndim - 1)) + (0, bits.ndim - 1)
        acc = jnp.transpose(acc, perm)
    out_bits = _mod2(acc).reshape(lead + (m_bits.shape[0], P, 8))
    weights = jnp.array(_BIT_SHIFTS, dtype=jnp.int32)
    return jnp.sum(out_bits * weights, axis=-1).astype(jnp.uint8)


@functools.lru_cache(maxsize=256)
def _packet_fn(bits_key: bytes, shape_key: tuple, w: int, packetsize: int,
               compute: str):
    rows, cols = shape_key
    m_bits = jnp.asarray(
        np.frombuffer(bits_key, dtype=np.uint8).reshape(rows, cols))

    @jax.jit
    def run(data):
        # data: (B, n, L) uint8, n*w == cols, L % (w*packetsize) == 0
        B, n, L = data.shape
        nblk = L // (w * packetsize)
        blocks = data.reshape(B, n, nblk, w, packetsize)
        packets = blocks.transpose(0, 2, 1, 3, 4).reshape(
            B, nblk, n * w, packetsize)
        out = gf2_packet_matmul(m_bits, packets, compute)
        r = rows // w
        out = out.reshape(B, nblk, r, w, packetsize).transpose(0, 2, 1, 3, 4)
        return out.reshape(B, r, nblk * w * packetsize)

    return run


def make_packet_codec_fn(matrix: np.ndarray, w: int, packetsize: int,
                         compute: str = DEFAULT_COMPUTE):
    """Jitted packetized transform from a GF(2^w) byte matrix.

    matrix: (r, c) uint8 -> fn(data (B, c, L) or (c, L)) -> (B, r, L)
    parity in jerasure bitmatrix chunk layout (bit-identical to the
    reference's packetized encode).
    """
    bits = gf.expand_bitmatrix(np.asarray(matrix, dtype=np.uint8), w)
    return make_bits_codec_fn(bits, w, packetsize, compute)


def make_bits_codec_fn(bits: np.ndarray, w: int, packetsize: int,
                       compute: str = DEFAULT_COMPUTE):
    """Jitted packetized transform from a raw GF(2) bit-matrix
    (liberation / blaum_roth minimal-density codes, which have no
    byte-matrix form)."""
    bits = np.asarray(bits, dtype=np.uint8)
    fn = _packet_fn(bits.tobytes(), bits.shape, w, packetsize, compute)

    def call(data):
        data = jnp.asarray(data, dtype=jnp.uint8)
        squeeze = data.ndim == 2
        if squeeze:
            data = data[None]
        out = fn(data)
        return out[0] if squeeze else out

    return call


# ---------------------------------------------------------------------------
# Device CRC32C
# ---------------------------------------------------------------------------

DEFAULT_CRC_BLOCK = 16  # bytes; 8W = 128 bits fills one MXU column exactly


CRC_GROUP = 64


@functools.lru_cache(maxsize=64)
def _crc_fn(nbytes: int, block: int, compute: str):
    in_dtype, acc_dtype = _COMPUTE_DTYPES[compute]
    nblk = nbytes // block
    hierarchical = nblk % CRC_GROUP == 0 and nblk >= CRC_GROUP
    if hierarchical:
        fold_np, gcomb_np, top_np = crc_mod.block_crc_matrices_2level(
            nbytes, block, CRC_GROUP)
        gcomb = jnp.asarray(gcomb_np)
        top = jnp.asarray(top_np)
    else:
        fold_np, comb_np = crc_mod.block_crc_matrices(nbytes, block)
        comb = jnp.asarray(comb_np)
    fold = jnp.asarray(fold_np)          # (32, 8*block)
    weights32 = jnp.asarray([1 << i for i in range(32)], dtype=jnp.uint32)

    @jax.jit
    def run(chunks):
        # chunks: (..., L) uint8; bits byte-major LSB-first to match
        # crc32c.message_matrix's column convention.
        lead = chunks.shape[:-1]
        blocks = chunks.reshape(lead + (nblk, block))
        shifts = jnp.arange(8, dtype=jnp.uint8)
        bits = (blocks[..., None] >> shifts) & jnp.uint8(1)   # (..., nblk, block, 8)
        bits = bits.reshape(lead + (nblk, block * 8)).astype(in_dtype)
        # fold every block with the shared matrix: (..., nblk, 32)
        r = jax.lax.dot_general(
            bits, fold.astype(in_dtype),
            dimension_numbers=(((bits.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=acc_dtype,
        )
        r = _mod2(r).astype(in_dtype)
        if hierarchical:
            ngroups = nblk // CRC_GROUP
            rg = r.reshape(lead + (ngroups, CRC_GROUP, 32))
            s = jnp.einsum("tvu,...gtu->...gv", gcomb.astype(in_dtype), rg,
                           preferred_element_type=acc_dtype)
            s = _mod2(s).astype(in_dtype)
            acc = jnp.einsum("gvu,...gu->...v", top.astype(in_dtype), s,
                             preferred_element_type=acc_dtype)
        else:
            acc = jnp.einsum("nvu,...nu->...v", comb.astype(in_dtype), r,
                             preferred_element_type=acc_dtype)
        bits_out = _mod2(acc).astype(jnp.uint32)
        return jnp.sum(bits_out * weights32, axis=-1, dtype=jnp.uint32)

    return run


def make_crc_fn(nbytes: int, block: int = DEFAULT_CRC_BLOCK,
                compute: str = DEFAULT_COMPUTE):
    """Jitted CRC32C (seed 0) over the last axis: (..., L) uint8 -> (...) uint32.

    Seed chaining is applied on the host via crc32c.crc32c_combine (a 32x32
    matvec) — the heavy lifting (the message fold) stays on device.
    """
    if nbytes % block:
        block = _pick_block(nbytes)
    return _crc_fn(nbytes, block, compute)


def _pick_block(nbytes: int) -> int:
    for b in (128, 64, 32, 16, 8, 4, 2, 1):
        if nbytes % b == 0:
            return b
    return 1


# ---------------------------------------------------------------------------
# Fused encode + scrub CRC (the north-star pass)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=64)
def _encode_crc_fn(g_bits_key: bytes, shape_key: tuple, nbytes: int,
                   block: int, compute: str, witness_only: bool = False):
    rows, cols = shape_key
    g_bits = np.frombuffer(g_bits_key, dtype=np.uint8).reshape(rows, cols)
    g_const = jnp.asarray(g_bits)
    crc = _crc_fn(nbytes, block, compute)

    @jax.jit
    def run(data):
        parity = gf2_matmul_bytes_packed(g_const, data, compute)
        chunks = jnp.concatenate([data, parity], axis=-2)
        return crc(chunks) if witness_only else (parity, crc(chunks))

    return run


def encode_readback_bytes(B: int, k: int, m: int, L: int) -> int:
    """Exact D2H bytes one fused encode+CRC dispatch of a (B, k, L)
    batch fetches: the (B, m, L) parity block plus the 4-byte CRC per
    chunk — the data shards the host already holds are NEVER echoed
    back.  bench --smoke gates the transfer plane's bytes_d2h counter
    on this identity."""
    return B * m * L + 4 * B * (k + m)


def make_encode_crc_fn(matrix: np.ndarray, nbytes: int,
                       block: int = DEFAULT_CRC_BLOCK,
                       compute: str = DEFAULT_COMPUTE):
    """fn(data (B, k, L)) -> (parity (B, m, L), crcs (B, k+m) uint32).

    One device dispatch per batch: chunks cross PCIe once (parity-only
    readback: the return tuple is exactly what crosses D2H — see
    encode_readback_bytes), encode matmul and scrub CRC fold share the
    on-device bit expansion.
    """
    bits = gf.expand_bitmatrix(np.asarray(matrix, dtype=np.uint8), 8)
    if nbytes % block:
        block = _pick_block(nbytes)
    return _encode_crc_fn(bits.tobytes(), bits.shape, nbytes, block, compute)


def make_encode_crc_witness_fn(matrix: np.ndarray, nbytes: int,
                               block: int = DEFAULT_CRC_BLOCK,
                               compute: str = DEFAULT_COMPUTE):
    """Benchmark/scrub variant: fn(data (B, k, L)) -> crcs (B, k+m) uint32.

    Parity never leaves the device — only the 32-bit-per-chunk scrub
    checksums come back, so the host<->device link carries k*L in and
    4*(k+m) out.  The CRCs depend on every parity byte, so the full encode
    provably executes.
    """
    bits = gf.expand_bitmatrix(np.asarray(matrix, dtype=np.uint8), 8)
    if nbytes % block:
        block = _pick_block(nbytes)
    return _encode_crc_fn(bits.tobytes(), bits.shape, nbytes, block, compute,
                          witness_only=True)


# ---------------------------------------------------------------------------
# Mesh-sharded kernels (pod-scale: ONE batch across the device mesh)
#
# A single mega-batch larger than one chip's HBM cannot ride a dispatch
# lane; it CAN ride the whole mesh.  The GF(2^8) encode matmul is
# row-local in the chunk-length axis L (parity byte l depends only on
# data bytes at position l), so shard_map-ing L across an "ls" mesh
# axis needs NO communication for the parity — each device encodes its
# L-slice against the full generator.  The per-chunk scrub CRC is
# GF(2)-linear in the message under seed 0, so each device folds its
# slice locally, advances the partial through the zero-advance matrix
# for the bytes that FOLLOW its slice (crc32c.advance_matrix), and an
# XOR psum over "ls" combines the partials ON DEVICE — the only CRC
# bytes that cross D2H are the final 4 per chunk.
#
# L that does not divide by the mesh width is FRONT-padded with zeros:
# with seed 0 the CRC register stays 0 through leading zero bytes, so
# crc(0^pad || chunk) == crc(chunk), and the parity of the pad columns
# is itself zero — both outputs slice back exactly.  An optional "dp"
# axis additionally shards the stripe axis (conf osd_ec_device_mesh
# "AxB"); S pads with zero stripes the caller slices off.
# ---------------------------------------------------------------------------


def _crc_bits_u32(c: jnp.ndarray) -> jnp.ndarray:
    """(...,) uint32 -> (..., 32) 0/1 bits, bit i = (crc >> i) & 1
    (the crc32c GF(2) state convention)."""
    shifts = jnp.arange(32, dtype=jnp.uint32)
    return ((c[..., None] >> shifts) & jnp.uint32(1))


def mesh_geometry(nbytes: int, n_ls: int) -> tuple[int, int, int]:
    """(L_pad, Lp, pad) for sharding an L=nbytes chunk axis over n_ls
    devices: L front-pads to the next multiple of n_ls."""
    L_pad = -(-nbytes // n_ls) * n_ls
    return L_pad, L_pad // n_ls, L_pad - nbytes


def _mesh_context(devices, n_dp: int, n_ls: int):
    """Build the dp x ls jax Mesh plus the sharding/shard_map imports
    shared by the mesh kernel builders."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    try:
        from jax import shard_map          # jax >= 0.8
    except ImportError:
        from jax.experimental.shard_map import shard_map
    devs = np.array(list(devices)).reshape(n_dp, n_ls)
    return jax, Mesh(devs, ("dp", "ls")), NamedSharding, P, shard_map


def _slice_combine_matrices(n_ls: int, Lp: int) -> np.ndarray:
    """(n_ls, 32, 32) GF(2): slice j's CRC partial advanced over the
    (n_ls-1-j)*Lp bytes that follow it, so XOR over j yields the full
    chunk CRC (linearity of seed-0 CRC32C in the message bits)."""
    return np.stack([crc_mod.advance_matrix((n_ls - 1 - j) * Lp)
                     for j in range(n_ls)]).astype(np.uint8)


def _combine_local_crcs(jax, c, comb_c, in_dtype, acc_dtype):
    """Advance this shard's (..., km) uint32 CRC partials by its slice
    position and XOR-psum over the "ls" axis -> full (..., km) CRCs."""
    idx = jax.lax.axis_index("ls")
    M = comb_c[idx]                              # (32, 32), static per device
    bits = _crc_bits_u32(c).astype(in_dtype)
    adv = jnp.einsum("vu,...u->...v", M.astype(in_dtype), bits,
                     preferred_element_type=acc_dtype)
    tot = jax.lax.psum(_mod2(adv), "ls")         # GF(2) add == XOR
    full = (tot & 1).astype(jnp.uint32)
    weights32 = jnp.asarray([1 << i for i in range(32)], dtype=jnp.uint32)
    return jnp.sum(full * weights32, axis=-1, dtype=jnp.uint32)


def _donated_call(fn, *args):
    """Call a possibly-donating jitted fn; backends without donation
    support (CPU in older jax) warn instead of failing — silence it,
    the arena lifecycle upstream is identical either way."""
    import warnings
    with warnings.catch_warnings():
        warnings.filterwarnings("ignore", message=".*[Dd]onat.*")
        return fn(*args)


def make_mesh_encode_crc_fn(matrix: np.ndarray, nbytes: int, devices,
                            n_dp: int = 1, n_ls: int | None = None,
                            compute: str = DEFAULT_COMPUTE,
                            donate: bool = False):
    """Mesh-sharded fused encode+CRC over len(devices) chips.

    Returns run(batch (S, k, L=nbytes) uint8, keep_resident=False) ->
    (parity (S, m, L) uint8, crcs (S, k+m) uint32, resident) with
    outputs BIT-IDENTICAL to the single-device fused kernel / host
    oracle.  resident is None, or (dev_data, dev_parity, chunk_pad) —
    the mesh-sharded device arrays for the HBM stripe cache — when
    keep_resident is asked and the input was not donated.

    `donate` compiles with donate_argnums so the staged input buffer
    is DONATED to the computation: its device allocation is consumed
    (XLA may alias it for outputs) and the uploaded bytes are never
    echoed — the staging arena copy becomes the H2D upload itself.
    """
    devices = tuple(devices)
    if n_ls is None:
        n_ls = len(devices) // max(1, n_dp)
    if n_dp * n_ls != len(devices):
        raise ValueError(f"mesh {n_dp}x{n_ls} != {len(devices)} devices")
    jax_mod, mesh, NamedSharding, P, shard_map = _mesh_context(
        devices, n_dp, n_ls)
    in_dtype, acc_dtype = _COMPUTE_DTYPES[compute]
    bits = gf.expand_bitmatrix(np.asarray(matrix, dtype=np.uint8), 8)
    g_const = jnp.asarray(bits)
    k = bits.shape[1] // 8
    m = bits.shape[0] // 8
    L = int(nbytes)
    L_pad, Lp, pad = mesh_geometry(L, n_ls)
    block = DEFAULT_CRC_BLOCK if Lp % DEFAULT_CRC_BLOCK == 0 \
        else _pick_block(Lp)
    crc_local = _crc_fn(Lp, block, compute)
    comb_c = jnp.asarray(_slice_combine_matrices(n_ls, Lp))

    def local_fn(local):
        # local: (S/n_dp, k, Lp) — this device's chunk-length slice
        parity = gf2_matmul_bytes_packed(g_const, local, compute)
        chunks = jnp.concatenate([local, parity], axis=-2)
        c = crc_local(chunks)                       # (s, k+m) partials
        full = _combine_local_crcs(jax_mod, c, comb_c, in_dtype,
                                   acc_dtype)
        return parity, full

    sharded = shard_map(local_fn, mesh=mesh,
                        in_specs=(P("dp", None, "ls"),),
                        out_specs=(P("dp", None, "ls"), P("dp", None)))
    jitted = jax_mod.jit(sharded, donate_argnums=(0,) if donate else ())
    data_sharding = NamedSharding(mesh, P("dp", None, "ls"))

    def run(batch: np.ndarray, keep_resident: bool = False):
        S = batch.shape[0]
        S_pad = -(-S // n_dp) * n_dp
        arr = batch
        if pad or S_pad != S:
            # uneven geometry: front-pad L (leading zeros are CRC- and
            # parity-neutral) and tail-pad S with zero stripes — a
            # real host copy of the whole batch, audited so the mesh
            # path's copy story stays honest even when a degraded
            # plane's width stops dividing L
            arr = np.zeros((S_pad, k, L_pad), dtype=np.uint8)
            arr[:S, :, pad:] = batch
            from ..utils import copyaudit
            copyaudit.note("ec.mesh_pad", batch.nbytes)
        dev = jax_mod.device_put(arr, data_sharding)
        parity_dev, crcs_dev = _donated_call(jitted, dev)
        crcs = np.asarray(crcs_dev)[:S]
        parity = np.asarray(parity_dev)
        if pad or S_pad != S:
            parity = parity[:S, :, pad:]
        resident = None
        if keep_resident and not donate:
            resident = (dev, parity_dev, pad)
        return parity, crcs, resident

    run.chunk_pad = pad
    run.mesh_devices = devices
    return run


def make_mesh_crc_fn(nbytes: int, devices, n_dp: int = 1,
                     n_ls: int | None = None,
                     compute: str = DEFAULT_COMPUTE):
    """Mesh-sharded CRC32C(seed 0) fold: run(batch (B, nbytes) uint8)
    -> (B,) uint32, the deep-scrub channel's mega-batch form.  Each
    device folds its slice of every row; partials combine on device
    (advance + XOR psum) so D2H is 4 bytes per row."""
    devices = tuple(devices)
    if n_ls is None:
        n_ls = len(devices) // max(1, n_dp)
    if n_dp * n_ls != len(devices):
        raise ValueError(f"mesh {n_dp}x{n_ls} != {len(devices)} devices")
    jax_mod, mesh, NamedSharding, P, shard_map = _mesh_context(
        devices, n_dp, n_ls)
    in_dtype, acc_dtype = _COMPUTE_DTYPES[compute]
    L = int(nbytes)
    L_pad, Lp, pad = mesh_geometry(L, n_ls)
    block = DEFAULT_CRC_BLOCK if Lp % DEFAULT_CRC_BLOCK == 0 \
        else _pick_block(Lp)
    crc_local = _crc_fn(Lp, block, compute)
    comb_c = jnp.asarray(_slice_combine_matrices(n_ls, Lp))

    def local_fn(local):
        c = crc_local(local)                        # (b,) partials
        return _combine_local_crcs(jax_mod, c, comb_c, in_dtype,
                                   acc_dtype)

    sharded = shard_map(local_fn, mesh=mesh,
                        in_specs=(P("dp", "ls"),),
                        out_specs=P("dp"))
    jitted = jax_mod.jit(sharded)
    data_sharding = NamedSharding(mesh, P("dp", "ls"))

    def run(batch: np.ndarray):
        B = batch.shape[0]
        B_pad = -(-B // n_dp) * n_dp
        arr = batch
        if pad or B_pad != B:
            arr = np.zeros((B_pad, L_pad), dtype=np.uint8)
            arr[:B, pad:] = batch
        dev = jax_mod.device_put(arr, data_sharding)
        return np.asarray(jitted(dev))[:B]

    run.chunk_pad = pad
    run.mesh_devices = devices
    return run
