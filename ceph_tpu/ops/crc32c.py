"""CRC32C (Castagnoli) — host reference + GF(2) matrix algebra for TPU.

Semantics match the reference's ceph_crc32c (common/crc32c.h): the seed is
the raw initial register value with **no pre/post inversion** (callers pass
-1 and xor at the edges when they want the RFC flavor), reflected bit
order, polynomial 0x1EDC6F41.  `bufferlist::crc32c(seed)` chains calls by
feeding the previous result as the next seed; HashInfo in the EC path
(osd/ECUtil.cc:140 in the reference) relies on exactly that chaining.

The device story: CRC32C is GF(2)-linear in the message bits for a fixed
length, so
    crc(seed, msg) = S_L @ bits(seed)  ^  C @ bits(msg)        (mod 2)
where S_L is a 32x32 "advance seed by L bytes" matrix and C is block
structured.  We factor C in two levels so the per-length matrices stay
small:  split the message into W-byte blocks, fold each block with the
*same* 32x(8W) matrix (a position-independent matmul, MXU-friendly), then
combine the per-block 32-bit remainders with per-position 32x32 matrices.
`ceph_tpu.ops.ec_kernels` consumes these matrices.
"""

from __future__ import annotations

import functools

import numpy as np

CASTAGNOLI_POLY = 0x1EDC6F41
# Reflected (LSB-first) polynomial representation used by the byte-wise
# right-shift algorithm.
POLY_REFLECTED = 0x82F63B78


@functools.lru_cache(maxsize=1)
def _table() -> np.ndarray:
    t = np.zeros(256, dtype=np.uint32)
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ (POLY_REFLECTED if (c & 1) else 0)
        t[i] = c
    return t


def crc32c_sw(seed: int, data: bytes | np.ndarray) -> int:
    """Bytewise table CRC32C, ceph raw-seed semantics (no inversions)."""
    t = _table()
    crc = seed & 0xFFFFFFFF
    buf = data.tobytes() if isinstance(data, np.ndarray) else bytes(data)
    for b in buf:
        crc = (crc >> 8) ^ int(t[(crc ^ b) & 0xFF])
    return crc & 0xFFFFFFFF


def crc32c(seed: int, data: bytes | np.ndarray) -> int:
    """Host CRC32C: native sliced-by-8 C++ when built, else bytewise."""
    from .. import native
    got = native.crc32c(seed, data)
    if got is not None:
        return got
    return crc32c_sw(seed, data)


def crc32c_std(data: bytes) -> int:
    """RFC-flavor CRC32C (init/xorout 0xffffffff) for test vectors."""
    return crc32c_sw(0xFFFFFFFF, data) ^ 0xFFFFFFFF


@functools.lru_cache(maxsize=1)
def _slice8_tables() -> np.ndarray:
    """(8, 256) uint32 slicing-by-8 tables: tables[j][b] is the CRC
    register after byte b followed by j zero bytes — table 0 folded
    forward through the zero-byte advance (the same combine algebra as
    advance_matrix, collapsed to a byte lookup)."""
    t = np.zeros((8, 256), dtype=np.uint32)
    t[0] = _table()
    for j in range(1, 8):
        prev = t[j - 1]
        t[j] = (prev >> 8) ^ t[0][prev & 0xFF]
    return t


def crc32c_batch(arr: np.ndarray, seed: int = 0) -> np.ndarray:
    """CRC32C per row of an (N, L) uint8 array -> (N,) uint32.

    Raw-seed semantics (crc32c_sw).  The native sliced-by-8 C++ kernel
    serves each row when built; the fallback is a slicing-by-8 update
    vectorized across the batch axis (8 table lookups fold 8 bytes of
    every row per step), so a degraded host path folds a whole scrub
    batch without the per-byte python loop.
    """
    arr = np.ascontiguousarray(arr, dtype=np.uint8)
    if arr.ndim == 1:
        arr = arr[None]
    N, L = arr.shape
    from .. import native
    got = native.crc32c_batch(seed, arr)
    if got is not None:
        return got
    t = _slice8_tables()
    crc = np.full(N, seed & 0xFFFFFFFF, dtype=np.uint32)
    n8 = L - (L % 8)
    if n8:
        blocks = arr[:, :n8].reshape(N, n8 // 8, 8)
        for j in range(n8 // 8):
            b = blocks[:, j, :].astype(np.uint32)
            crc = (t[7][(crc ^ b[:, 0]) & 0xFF]
                   ^ t[6][((crc >> 8) ^ b[:, 1]) & 0xFF]
                   ^ t[5][((crc >> 16) ^ b[:, 2]) & 0xFF]
                   ^ t[4][((crc >> 24) ^ b[:, 3]) & 0xFF]
                   ^ t[3][b[:, 4]] ^ t[2][b[:, 5]]
                   ^ t[1][b[:, 6]] ^ t[0][b[:, 7]])
    for j in range(n8, L):
        crc = (crc >> 8) ^ t[0][(crc ^ arr[:, j]) & 0xFF]
    return crc


# ---------------------------------------------------------------------------
# GF(2) linear-algebra view
#
# State convention: the CRC register as a 32-vector, bit i = (crc >> i) & 1.
# Message bits enter LSB-first per byte (reflected CRC).  All matrices act
# as out = (M @ in) % 2.
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def advance_matrix(nbytes: int) -> np.ndarray:
    """32x32 matrix A with crc(seed, 0^n) = A @ bits(seed) (zero message).

    Computed by squaring: advancing over zero bytes is linear in the state.
    """
    M1 = _byte_step_zero()
    out = np.eye(32, dtype=np.uint8)
    base = M1
    n = nbytes
    while n:
        if n & 1:
            out = (base @ out) % 2
        base = (base @ base) % 2
        n >>= 1
    return out.astype(np.uint8)


@functools.lru_cache(maxsize=1)
def _byte_step_zero() -> np.ndarray:
    """32x32 state transition for one zero message byte."""
    M = np.zeros((32, 32), dtype=np.uint8)
    for i in range(32):
        s = crc32c_sw(1 << i, b"\x00")
        for r in range(32):
            if (s >> r) & 1:
                M[r, i] = 1
    return M


@functools.lru_cache(maxsize=None)
def message_matrix(nbytes: int) -> np.ndarray:
    """32 x (8*nbytes) matrix C: crc(0, msg) = C @ msgbits.

    msgbits ordering: byte-major, LSB-first within each byte (matches
    np.unpackbits(..., bitorder='little') on the raw bytes).
    """
    cols = 8 * nbytes
    M = np.zeros((32, cols), dtype=np.uint8)
    # contribution of bit b of byte j = crc of message with only that bit
    # set; linearity lets us build columns independently — but one crc call
    # per column is O(n^2). Instead: column of (byte j, bit b) equals
    # advance_{n-1-j} applied to the 32-vec state after feeding that single
    # byte from zero state.
    for b in range(8):
        s0 = crc32c_sw(0, bytes([1 << b]))
        v0 = _u32_to_bits(s0)
        for j in range(nbytes):
            A = advance_matrix(nbytes - 1 - j)
            M[:, j * 8 + b] = (A @ v0) % 2
    return M


def _u32_to_bits(x: int) -> np.ndarray:
    return np.array([(x >> i) & 1 for i in range(32)], dtype=np.uint8)


def _bits_to_u32(v: np.ndarray) -> int:
    return int(sum(int(b) << i for i, b in enumerate(np.asarray(v) & 1)))


@functools.lru_cache(maxsize=None)
def block_crc_matrices(nbytes: int, block: int) -> tuple[np.ndarray, np.ndarray]:
    """Two-level factorization for device CRC of `nbytes`-long chunks.

    Returns (fold, combine):
      fold:    (32, 8*block) uint8 — same for every block: r_j = fold @ bits(block_j)
      combine: (nblocks, 32, 32) uint8 — crc(0,msg) = xor_j combine[j] @ r_j
    nbytes must be a multiple of block.
    """
    assert nbytes % block == 0
    nblocks = nbytes // block
    fold = message_matrix(block)
    combine = np.stack([advance_matrix((nblocks - 1 - j) * block)
                        for j in range(nblocks)], axis=0)
    return fold, combine


@functools.lru_cache(maxsize=None)
def block_crc_matrices_2level(nbytes: int, block: int, group: int
                              ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Hierarchical factorization: fold blocks, fold groups, combine groups.

    Returns (fold, gcombine, top):
      fold:     (32, 8*block)          r_j   = fold @ bits(block_j)
      gcombine: (group, 32, 32)        s_g   = xor_t gcombine[t] @ r_{g*group+t}
      top:      (ngroups, 32, 32)      crc   = xor_g top[g] @ s_g
    The group-relative matrices are position-independent, so the big
    per-position table of the flat factorization collapses to
    group + nbytes/(block*group) small matrices.
    """
    assert nbytes % (block * group) == 0
    ngroups = nbytes // (block * group)
    fold = message_matrix(block)
    gcombine = np.stack([advance_matrix((group - 1 - t) * block)
                         for t in range(group)], axis=0)
    top = np.stack([advance_matrix((ngroups - 1 - g) * block * group)
                    for g in range(ngroups)], axis=0)
    return fold, gcombine, top


def crc32c_combine(crc_a: int, crc_b: int, len_b: int) -> int:
    """crc(seed->a over A) then over B == combine(a, crc(0,B), len(B)).

    The classic crc combine: advance a's register over len_b zero bytes and
    xor with b's register.
    """
    A = advance_matrix(len_b)
    return _bits_to_u32((A @ _u32_to_bits(crc_a)) % 2) ^ crc_b


def crc32c_linear(seed: int, data: bytes) -> int:
    """Reference implementation of the matrix formulation (for tests)."""
    n = len(data)
    bits = np.unpackbits(np.frombuffer(data, dtype=np.uint8), bitorder="little")
    C = message_matrix(n)
    A = advance_matrix(n)
    v = ((C @ bits) + (A @ _u32_to_bits(seed))) % 2
    return _bits_to_u32(v)
