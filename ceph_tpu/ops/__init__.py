"""Device + host math kernels: GF(2^8), bit-matrices, CRC32C."""
