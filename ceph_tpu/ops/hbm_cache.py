"""HBM-resident EC stripe cache: bytes cross the host<->device
boundary at most once per object lifetime.

PR 2/3 amortized dispatch COUNT; the remaining e2e gap is pure
transfer: every producer re-uploaded bytes the device had already
seen.  An OSD's EC working set is written once and then re-touched by
deep scrub (CRC folds over the same shard bytes) and recovery
(decodes of the same stripes) — so after the write's single H2D
upload the encoded stripes simply STAY in HBM:

  * the pipeline stages an entry at collect time (device slices of the
    uploaded data and the computed parity — no extra transfer, the
    arrays are already device-resident) keyed (pg collection, oid);
  * the producer COMMITS the entry once the shard bytes landed in the
    object store, so the cache can never be ahead of disk;
  * deep scrub serves shard CRCs from the entry's per-stripe chunk
    CRCs (a host-side carry-less fold of 4-byte values — ZERO bytes
    re-uploaded, zero device dispatches);
  * recovery/degraded reads fetch the wanted shard rows D2H straight
    from the cached device arrays — no shard gather, no decode matmul,
    no H2D.

Coherence is enforced at the OBJECT STORE layer, not by trusting
producers: every applied transaction is scanned
(:func:`note_store_txn`) and any data mutation of a cached object's
shard files invalidates the entry — UNLESS the same transaction
attests the entry's exact version via the per-shard version xattr
(the EC write fan-out and recovery pushes of the same version are the
cached content landing on more shards, not new content).  A raw
store write with no version attestation — silent bitrot, a test
poking corruption in, a rollback stash restore — always invalidates,
so a cache hit is as trustworthy as the disk read it replaces and
deep scrub keeps catching real corruption.

Quarantine-aware eviction: entries are pinned to the pipeline lane
whose chip holds their HBM; when a lane quarantines (device error,
real or injected) its entries drop immediately — a redrain re-uploads
from host rather than ever serving shards from a chip in an unknown
state.

Capacity is bounded by ``osd_ec_hbm_cache_bytes`` (LRU on committed
entries); 0 disables the cache entirely.
"""

from __future__ import annotations

import ast
import threading
from collections import OrderedDict

import numpy as np

DEFAULT_CAPACITY = 64 << 20
MAX_PENDING = 64

# per-shard version xattr (osd/pglog.py VER_KEY): the store-txn
# coherence scan parses it to recognize same-version fan-out writes.
# Duplicated here because the ops layer must not import the osd layer.
_VER_ATTR = "_v"


def _base_name(name: str) -> str:
    """Base object of a shard/stash file name: 'oid.s3@1.7' -> 'oid'."""
    base = name.split("@", 1)[0]
    stem, _, sfx = base.rpartition(".s")
    if sfx.isdigit():
        return stem
    return base


def _parse_ver(blob: bytes) -> tuple | None:
    try:
        ev = ast.literal_eval(blob.decode())
    except (ValueError, SyntaxError, UnicodeDecodeError, AttributeError):
        return None
    return tuple(ev) if isinstance(ev, tuple) else None


class CacheIntent:
    """Producer-side tag riding a pipeline submission: 'if this encode
    runs on a device, keep its stripes in HBM under this key'."""

    __slots__ = ("cid", "oid", "version", "size", "chunk_size")

    def __init__(self, cid: str, oid: str, version: tuple,
                 size: int, chunk_size: int):
        self.cid = cid
        self.oid = oid
        self.version = tuple(version)
        self.size = int(size)
        self.chunk_size = int(chunk_size)


def _on_lane(entry_lane, lane: int) -> bool:
    """Whether an entry is resident on `lane`: single-lane entries pin
    an int, MESH-resident entries (stripes sharded across the device
    mesh) pin the tuple of every member lane — losing any one chip
    loses a slice of the stripes, so membership means resident."""
    if isinstance(entry_lane, tuple):
        return lane in entry_lane
    return entry_lane == lane


class CacheEntry:
    """One object's encoded stripes, device-resident.

    dev_data (S, k, L) is the uploaded data batch, dev_parity
    (S, m, L) the on-device encode output — both still on the lane's
    chip (or sharded across a mesh's chips for a mesh dispatch, in
    which case `lane` is the member-lane tuple and `pad` the leading
    zero bytes each chunk was front-padded with for even sharding);
    crcs (S, k+m) uint32 are the fused kernel's per-stripe chunk
    CRCs (host-side, 4 bytes per chunk)."""

    __slots__ = ("cid", "oid", "version", "size", "chunk_size", "k",
                 "m", "dev_data", "dev_parity", "crcs", "lane",
                 "pad", "nbytes", "committed")

    def __init__(self, intent: CacheIntent, lane, dev_data,
                 dev_parity, crcs: np.ndarray, pad: int = 0):
        self.cid = intent.cid
        self.oid = intent.oid
        self.version = intent.version
        self.size = intent.size
        self.chunk_size = intent.chunk_size
        self.k = int(dev_data.shape[1])
        self.m = int(dev_parity.shape[1])
        self.dev_data = dev_data
        self.dev_parity = dev_parity
        self.crcs = np.asarray(crcs, dtype=np.uint32)
        self.lane = lane
        self.pad = int(pad)
        self.nbytes = (int(np.prod(dev_data.shape))
                       + int(np.prod(dev_parity.shape))
                       + self.crcs.nbytes)
        self.committed = False

    @property
    def stripes(self) -> int:
        return int(self.crcs.shape[0])

    def shard_size(self) -> int:
        return self.stripes * self.chunk_size

    def data_bytes(self):
        """The logical object payload, fetched D2H from the cached
        data stripes (None if the device buffers are gone).  Returns a
        zero-copy BufferList VIEW over the fetched array — the D2H
        fetch is the only materialization a cache-served read pays.
        Mesh entries strip each chunk's leading pad after the fetch
        (per-shard addressing keeps the padded on-device layout)."""
        try:
            arr = np.asarray(self.dev_data, dtype=np.uint8)
            get().count_d2h(arr.nbytes)
            if self.pad:
                # stripping each chunk's leading pad leaves a strided
                # view; serving it as one rope needs a contiguous
                # copy — a real read-path materialization, audited so
                # host_copies_per_read stays honest for padded mesh
                # entries
                arr = np.ascontiguousarray(arr[:, :, self.pad:])
                from ..utils import copyaudit
                copyaudit.note("cache.mesh_unpad", arr.nbytes)
            else:
                arr = np.ascontiguousarray(arr)
        except Exception:
            return None
        from ..utils.bufferlist import BufferList
        rope = BufferList(memoryview(arr.reshape(-1))[: self.size])
        get().count_read_hit_bytes(self.size)
        return rope

    def shard_bytes(self, shard: int) -> bytes | None:
        """One shard file's bytes (chunk `shard` of every stripe),
        fetched D2H — only this shard's rows cross the boundary (for
        a mesh entry: that row's slice from each member chip)."""
        try:
            if shard < self.k:
                arr = np.asarray(self.dev_data[:, shard],
                                 dtype=np.uint8)
            else:
                arr = np.asarray(self.dev_parity[:, shard - self.k],
                                 dtype=np.uint8)
        except Exception:
            return None
        get().count_d2h(arr.nbytes)
        if self.pad:
            arr = arr[:, self.pad:]
        return arr.tobytes()


class HbmStripeCache:
    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._entries: OrderedDict[tuple, CacheEntry] = OrderedDict()
        self._pending: OrderedDict[tuple, CacheEntry] = OrderedDict()
        self._bases: set[tuple] = set()     # committed + pending keys
        self._bytes = 0                     # committed entries
        self._pbytes = 0                    # pending (staged) entries
        self._c = {"hit": 0, "miss": 0, "evict": 0, "insert": 0,
                   "invalidate": 0, "lane_drops": 0, "bytes_d2h": 0,
                   "read_bytes_served": 0, "append_throughs": 0}

    # -- accounting (entry fetches call back in) ---------------------------

    def count_d2h(self, n: int) -> None:
        with self._lock:
            self._c["bytes_d2h"] += int(n)

    def count_read_hit_bytes(self, n: int) -> None:
        """Logical payload bytes a read served from the cache (the
        bench's read_cache_gbs numerator)."""
        with self._lock:
            self._c["read_bytes_served"] += int(n)

    # -- write path --------------------------------------------------------

    def stage(self, intent: CacheIntent, lane, dev_data,
              dev_parity, crcs: np.ndarray, pad: int = 0) -> None:
        """Pipeline collect-time staging: the entry exists but is NOT
        servable until the producer commits it (shard bytes on disk).
        `lane` is an int for a single-chip dispatch or the member-lane
        tuple for a mesh dispatch (sharded residency); `pad` is the
        mesh path's per-chunk leading zero pad."""
        if self.capacity <= 0:
            return
        try:
            ent = CacheEntry(intent, lane, dev_data, dev_parity, crcs,
                             pad=pad)
        except Exception:
            return
        if ent.nbytes > self.capacity:
            return
        key = (ent.cid, ent.oid)
        with self._lock:
            old = self._pending.pop(key, None)
            if old is not None:
                self._pbytes -= old.nbytes
            self._pending[key] = ent
            self._pbytes += ent.nbytes
            self._bases.add(key)
            # pending entries pin device HBM just like committed ones:
            # bound the TOTAL resident bytes by the configured budget
            # (an orphaned stage — producer died before commit — must
            # not overcommit the chip).  Committed LRU victims go
            # first — commit() would evict exactly them on promotion
            # anyway; staler pendings go after
            while self._bytes + self._pbytes > self.capacity and \
                    self._entries:
                k2, old = self._entries.popitem(last=False)
                self._bytes -= old.nbytes
                self._c["evict"] += 1
                if k2 not in self._pending:
                    self._bases.discard(k2)
            while self._pending and (
                    len(self._pending) > MAX_PENDING or
                    self._bytes + self._pbytes > self.capacity):
                old_key, old = self._pending.popitem(last=False)
                self._pbytes -= old.nbytes
                if old_key not in self._entries:
                    self._bases.discard(old_key)

    def append_through(self, cid: str, oid: str, old_version: tuple,
                       new_version: tuple, new_size: int,
                       chunk_size: int, full_before: int,
                       tail_data, tail_parity,
                       tail_crcs: np.ndarray) -> bool:
        """APPEND write-through: derive the appended object's entry
        from the resident whole-object stripes plus the tail encode's
        (S_tail, k, L) data / (S_tail, m, L) parity stripes — the
        untouched full-stripe prefix never leaves the chip, only the
        tail crosses.  Stages a PENDING entry at `new_version` (the
        producer commits once the shard tail bytes are on disk, the
        same contract as a whole-object write); the store-txn scan
        then drops the old committed entry (its version is not
        attested) while the attested pending one survives.

        Returns False — after invalidating, so a stale whole-object
        entry can never outlive the append — when there is no
        resident entry at exactly `old_version` with this geometry,
        or the device-side concatenation fails; the caller loses
        nothing but the write-through."""
        key = (cid, oid)
        with self._lock:
            ent = self._entries.get(key) or self._pending.get(key)
        if self.capacity <= 0:
            return False
        if ent is None or ent.version != tuple(old_version) or \
                ent.chunk_size != chunk_size or \
                ent.stripes < full_before or \
                isinstance(ent.lane, tuple) or ent.pad:
            # mesh-resident entries don't append-through: the tail
            # concat would need resharding across the mesh — the
            # conservative invalidate keeps coherence semantics
            # identical and the next whole write restages
            self.invalidate(cid, oid)
            return False
        try:
            tail_data = np.ascontiguousarray(tail_data,
                                             dtype=np.uint8)
            tail_parity = np.ascontiguousarray(tail_parity,
                                               dtype=np.uint8)
            head_d = ent.dev_data[:full_before]
            head_p = ent.dev_parity[:full_before]
            dev = None
            devs = getattr(ent.dev_data, "devices", None)
            if callable(devs):
                try:
                    dev = next(iter(devs()))
                except Exception:
                    dev = None
            if dev is not None:
                # device-resident entry: upload only the tail and
                # concatenate ON the chip (the prefix never moves)
                import jax
                import jax.numpy as jnp
                td = jax.device_put(tail_data, dev)
                tp = jax.device_put(tail_parity, dev)
                new_d = jnp.concatenate([head_d, td]) \
                    if full_before else td
                new_p = jnp.concatenate([head_p, tp]) \
                    if full_before else tp
            else:
                new_d = np.concatenate(
                    [np.asarray(head_d, dtype=np.uint8), tail_data]) \
                    if full_before else tail_data
                new_p = np.concatenate(
                    [np.asarray(head_p, dtype=np.uint8), tail_parity]) \
                    if full_before else tail_parity
            new_crcs = np.concatenate(
                [np.asarray(ent.crcs)[:full_before],
                 np.asarray(tail_crcs, dtype=np.uint32)])
        except Exception:
            self.invalidate(cid, oid)
            return False
        intent = CacheIntent(cid, oid, tuple(new_version),
                             int(new_size), chunk_size)
        self.stage(intent, ent.lane, new_d, new_p, new_crcs)
        with self._lock:
            self._c["append_throughs"] += 1
        return True

    def commit(self, cid: str, oid: str, version: tuple) -> bool:
        """Promote the staged entry for (cid, oid) at `version`: the
        producer's store transaction applied, disk and HBM now agree."""
        key = (cid, oid)
        version = tuple(version)
        with self._lock:
            ent = self._pending.get(key)
            if ent is None or ent.version != version:
                return False
            del self._pending[key]
            self._pbytes -= ent.nbytes
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            ent.committed = True
            self._entries[key] = ent
            self._bases.add(key)
            self._bytes += ent.nbytes
            self._c["insert"] += 1
            while self._bytes > self.capacity and self._entries:
                k2, old = self._entries.popitem(last=False)
                self._bytes -= old.nbytes
                self._c["evict"] += 1
                if k2 not in self._pending:
                    self._bases.discard(k2)
            return True

    # -- read path ---------------------------------------------------------

    def lookup(self, cid: str, oid: str,
               version: tuple | None = None) -> CacheEntry | None:
        key = (cid, oid)
        with self._lock:
            ent = self._entries.get(key)
            if ent is None or (version is not None
                               and ent.version != tuple(version)):
                self._c["miss"] += 1
                return None
            self._entries.move_to_end(key)
            self._c["hit"] += 1
            return ent

    # -- invalidation ------------------------------------------------------

    def _drop_locked(self, key: tuple) -> None:
        ent = self._entries.pop(key, None)
        if ent is not None:
            self._bytes -= ent.nbytes
            self._c["invalidate"] += 1
        pend = self._pending.pop(key, None)
        if pend is not None:
            self._pbytes -= pend.nbytes
            if ent is None:
                self._c["invalidate"] += 1
        self._bases.discard(key)

    def invalidate(self, cid: str, oid: str) -> None:
        with self._lock:
            self._drop_locked((cid, oid))

    def invalidate_cid(self, cid: str) -> None:
        with self._lock:
            for key in [k for k in self._bases if k[0] == cid]:
                self._drop_locked(key)

    def note_mutation(self, cid: str, base: str,
                      attested: set[tuple]) -> None:
        """A store transaction mutated shard data of (cid, base).
        Keep the entry only when the txn attested the entry's exact
        version (same-version fan-out / recovery push of the cached
        content); anything else — corruption, rewind, a newer write —
        invalidates."""
        key = (cid, base)
        with self._lock:
            # committed and pending are judged INDEPENDENTLY: an
            # overwrite's txn attests the NEW version, which must keep
            # the fresh pending entry (its commit follows) while
            # dropping the stale committed one
            dropped = False
            ent = self._entries.get(key)
            if ent is not None and ent.version not in attested:
                del self._entries[key]
                self._bytes -= ent.nbytes
                dropped = True
            pend = self._pending.get(key)
            if pend is not None and pend.version not in attested:
                del self._pending[key]
                self._pbytes -= pend.nbytes
                dropped = True
            if dropped:
                self._c["invalidate"] += 1
            if key not in self._entries and key not in self._pending:
                self._bases.discard(key)

    def drop_lane(self, lane: int) -> None:
        """Quarantine-aware eviction: a quarantined chip's entries are
        gone — redrain re-uploads from host, never serves stale HBM.
        Only entries RESIDENT on that chip drop; the same object's
        committed/pending counterpart on a healthy lane survives."""
        with self._lock:
            dropped = 0
            for key in [k for k, e in self._entries.items()
                        if _on_lane(e.lane, lane)]:
                ent = self._entries.pop(key)
                self._bytes -= ent.nbytes
                dropped += 1
                if key not in self._pending:
                    self._bases.discard(key)
            for key in [k for k, e in self._pending.items()
                        if _on_lane(e.lane, lane)]:
                pend = self._pending.pop(key)
                self._pbytes -= pend.nbytes
                dropped += 1
                if key not in self._entries:
                    self._bases.discard(key)
            if dropped:
                self._c["lane_drops"] += dropped

    def drop_cids(self, cids) -> None:
        """Crash/abort of a daemon: every entry of its pg collections
        goes — a restarted daemon starts COLD, and in-process replicas
        of the same pg share the cid key, so the conservative drop is
        the only one that can never serve stripes whose backing store
        just lost its tail."""
        wanted = set(cids)
        if not wanted:
            return
        with self._lock:
            for key in [k for k in self._bases if k[0] in wanted]:
                self._drop_locked(key)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._pending.clear()
            self._bases.clear()
            self._bytes = 0
            self._pbytes = 0

    # -- store-txn coherence scan ------------------------------------------

    _DATA_OPS = {"write": 2, "zero": 2, "truncate": 2, "remove": 2,
                 "try_remove": 2, "clone": 3, "try_clone": 3}

    def note_txn_ops(self, ops: list[tuple]) -> None:
        """Scan one applied transaction's ops for mutations of cached
        objects' shard files (see module docstring for the
        version-attestation rule).  Cheap when nothing relevant is
        cached: one set lookup per mutating op.

        Ops targeting rollback STASH objects ('@' in the name — the
        same rule the scrubber skips them by) are not shard-file
        mutations: stashing a copy aside or trimming an acked stash
        never changes the current shard bytes (every EC write would
        otherwise self-invalidate at stash-trim time).  A stash
        RESTORE writes to the shard file itself and is caught by its
        destination name."""
        touched: dict[tuple, set] = {}
        mutated: set[tuple] = set()
        for op in ops:
            kind = op[0]
            idx = self._DATA_OPS.get(kind)
            if idx is not None:
                if "@" in op[idx]:
                    continue
                key = (op[1], _base_name(op[idx]))
                if key in self._bases:
                    mutated.add(key)
                    touched.setdefault(key, set())
            elif kind == "move":
                for cid, name in ((op[1], op[2]), (op[3], op[4])):
                    if "@" in name:
                        continue
                    key = (cid, _base_name(name))
                    if key in self._bases:
                        mutated.add(key)
                        touched.setdefault(key, set())
            elif kind == "setattr" and op[3] == _VER_ATTR:
                key = (op[1], _base_name(op[2]))
                if key in self._bases:
                    ver = _parse_ver(op[4])
                    if ver is not None:
                        touched.setdefault(key, set()).add(ver)
            elif kind == "rmcoll":
                if any(k[0] == op[1] for k in self._bases):
                    self.invalidate_cid(op[1])
        for key in mutated:
            self.note_mutation(key[0], key[1], touched.get(key, set()))

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._c)
            out["entries"] = len(self._entries)
            out["pending"] = len(self._pending)
            out["bytes"] = self._bytes
            out["pending_bytes"] = self._pbytes
            out["capacity"] = self.capacity
        return out

    def shrink_to_capacity(self) -> None:
        """LRU-evict committed (then oldest pending) entries until the
        resident bytes fit the current capacity — a runtime capacity
        DECREASE takes effect immediately, not at the next commit."""
        with self._lock:
            while self._bytes + self._pbytes > self.capacity and \
                    self._entries:
                key, old = self._entries.popitem(last=False)
                self._bytes -= old.nbytes
                self._c["evict"] += 1
                if key not in self._pending:
                    self._bases.discard(key)
            while self._bytes + self._pbytes > self.capacity and \
                    self._pending:
                key, old = self._pending.popitem(last=False)
                self._pbytes -= old.nbytes
                if key not in self._entries:
                    self._bases.discard(key)


# ---------------------------------------------------------------------------
# Process-wide singleton (the pipeline, every OSD in the process and
# the object stores all see one cache — same sharing model as the
# dispatch pipeline itself).
# ---------------------------------------------------------------------------

_global: HbmStripeCache | None = None
_glock = threading.Lock()


def get() -> HbmStripeCache:
    global _global
    if _global is None:
        with _glock:
            if _global is None:
                _global = HbmStripeCache()
    return _global


def configure(capacity_bytes: int | None = None) -> HbmStripeCache:
    c = get()
    if capacity_bytes is not None:
        c.capacity = int(capacity_bytes)
        if c.capacity <= 0:
            c.clear()
        else:
            c.shrink_to_capacity()
    return c


def note_store_txn(ops: list[tuple]) -> None:
    """Object-store hook: called for every applied transaction.  No-op
    (one attribute read) until something is cached."""
    c = _global
    if c is None or not c._bases:
        return
    try:
        c.note_txn_ops(ops)
    except Exception:
        # coherence scan must never fail a store apply; drop the whole
        # cache instead of risking a stale entry
        c.clear()


def stats() -> dict:
    return get().stats()
