"""Cross-op EC device pipeline: coalesce stripe work, amortize dispatch.

The kernels win by 5x (BENCH_r05: 30-50 GB/s vs ~6 GB/s host AVX2) but
the *op path* lost end-to-end: every EC write, scrub batch and rebuild
paid its own serial host->device->host round trip (~90 ms through the
axon tunnel) for a stripe batch worth ~1 ms of device time.  A storage
daemon has exactly the concurrency that amortizes a fixed dispatch
cost — many in-flight writes, scrub chunks and recovery rebuilds are
embarrassingly parallel stripes (SURVEY §5.7) — and the serial path
threw it away.

This module is the shared dispatcher all producers feed:

  * **channels** — a :class:`PipelineChannel` is one coalescable work
    class (same jitted kernel set): whole-object/append encodes of one
    (matrix, L), deep-scrub CRC folds of one shard size, rebuild
    decodes of one rows-matrix.  Items on one channel concatenate
    along the batch axis into a mega-batch.
  * **shape buckets** — mega-batches pad to a power-of-two stripe
    count (:func:`pad_batch`), so the device sees a small repeating
    shape set and jit recompiles stop after warm-up.
  * **overlapped dispatch** — up to ``depth`` device dispatches ride
    in flight at once (jax async dispatch): upload of batch N+1
    overlaps compute of batch N and fetch of batch N-1.  A collector
    thread blocks on the oldest fetch; the dispatcher keeps issuing.
  * **futures** — :meth:`EcDevicePipeline.submit` returns a
    ``concurrent.futures.Future`` resolving to ``(path, outputs)``,
    so an OSD op submits its encode, keeps journaling metadata, and
    collects parity+CRCs at commit time.
  * **degrade draining** — a device error (injected ``tpu_error`` or
    a real dispatch/fetch failure) notifies the channel owner (the
    tpu plugin degrades to the host matrix codec) and the affected
    batch plus everything still queued re-runs on the channel's host
    fn: no queued op is ever lost or corrupted.

Host batches run inline on the dispatcher thread — single-threaded
host execution is itself the coalescing backpressure: while one host
batch runs, new submissions queue and the next dispatch swallows them
all in one call.

Timing recorded per dispatch is the *marginal* service time (now
minus the later of dispatch-issue and previous-fetch-completion), so
an overlapped device dispatch records its amortized cost, not the
full tunnel latency — that is what makes the TpuBackend's measured
host/device routing produce a finite crossover.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future

import numpy as np

# defaults; daemons override via configure() from their conf
# (osd_ec_pipeline_depth / _coalesce_ms / _max_batch)
DEFAULT_DEPTH = 2
DEFAULT_COALESCE_WAIT = 0.002
DEFAULT_MAX_BATCH = 256

# liveness bounds: a device fetch that HANGS (no exception) must not
# become a process-wide EC outage.  The dispatcher declares a stall
# after STALL_TIMEOUT stuck behind a full overlap window and latches
# host-only dispatch; producers self-serve on host after
# RESULT_TIMEOUT blocked in result() (encode/CRC are pure functions
# of inputs they still hold, and the future's done() guard makes a
# late device resolution harmless).
STALL_TIMEOUT = 60.0
RESULT_TIMEOUT = 120.0


def next_bucket(n: int) -> int:
    """Power-of-two shape bucket for a batch of n stripes."""
    return 1 << (n - 1).bit_length() if n > 1 else 1


def pad_batch(batch: np.ndarray) -> np.ndarray:
    """Zero-pad axis 0 to the next power of two so device shapes
    repeat (jit is shape-specialized; a stable bucket set compiles
    once per size).  Callers slice the result back to the true count;
    host paths never pay the padding."""
    S = batch.shape[0]
    S_pad = next_bucket(S)
    if S_pad == S:
        return batch
    return np.concatenate(
        [batch, np.zeros((S_pad - S,) + batch.shape[1:], dtype=np.uint8)])


class PipelineChannel:
    """One coalescable work class.

    host_fn(batch) -> tuple of np arrays, each with leading dim ==
    batch.shape[0].  device_fn(padded_batch) -> same tuple of (lazy)
    device arrays, or None when the jitted fn is not warm yet (the
    batch then runs on host while a background compile proceeds).
    route(nbytes) -> True to try the device for a coalesced batch of
    that size.  on_error(exc) fires once per failed device attempt
    (the tpu plugin degrades there); record(path, nbytes, secs, depth)
    feeds the owner's measured-routing EMA.
    """

    __slots__ = ("key", "host_fn", "device_fn", "route", "on_error",
                 "record", "max_coalesce")

    def __init__(self, key, host_fn, device_fn=None, route=None,
                 on_error=None, record=None, max_coalesce=None):
        self.key = key
        self.host_fn = host_fn
        self.device_fn = device_fn
        self.route = route if route is not None else \
            (lambda nbytes: device_fn is not None)
        self.on_error = on_error or (lambda e: None)
        self.record = record or (lambda path, nbytes, secs, depth=1: None)
        self.max_coalesce = max_coalesce


class _Item:
    __slots__ = ("arr", "n", "fut", "t")

    def __init__(self, arr: np.ndarray):
        self.arr = arr
        self.n = arr.shape[0]
        self.fut: Future = Future()
        self.t = time.monotonic()


class _Dispatch:
    __slots__ = ("chan", "items", "S", "out", "t0", "nbytes")

    def __init__(self, chan, items, S, out, t0, nbytes):
        self.chan = chan
        self.items = items
        self.S = S
        self.out = out
        self.t0 = t0
        self.nbytes = nbytes


class EcDevicePipeline:
    def __init__(self, depth: int = DEFAULT_DEPTH,
                 coalesce_wait: float = DEFAULT_COALESCE_WAIT,
                 max_batch: int = DEFAULT_MAX_BATCH):
        self.depth = max(1, int(depth))
        self.coalesce_wait = float(coalesce_wait)
        self.max_batch = max(1, int(max_batch))
        self._lock = threading.Lock()
        # three predicates, one lock: queued work (dispatcher waits),
        # in-flight dispatches (collector waits), freed overlap slots
        # (dispatcher waits).  Separate conditions so a notify can
        # never wake the wrong thread and strand the right one.
        self._work_cv = threading.Condition(self._lock)
        self._inflight_cv = threading.Condition(self._lock)
        self._fetch_cv = threading.Condition(self._lock)
        self._queues: dict = {}            # chan.key -> deque[_Item]
        self._chans: dict = {}             # chan.key -> PipelineChannel
        self._inflight: deque = deque()    # _Dispatch awaiting fetch
        self._busy = 0                     # dispatches being processed
        self._stalled = False              # collector wedged: host-only
        self._running = False
        self._threads: list = []
        self._last_fetch_done = 0.0
        self._c = {
            "dispatches": 0, "dev_dispatches": 0, "host_dispatches": 0,
            "ops": 0, "stripes": 0, "coalesce_waits": 0,
            "device_errors": 0, "drained_to_host": 0,
            "max_queue_depth": 0,
        }

    # -- lifecycle ---------------------------------------------------------

    def _ensure_threads(self) -> None:
        if self._running:
            return
        self._running = True
        for name, target in (("ec-pipeline-dispatch", self._dispatch_loop),
                             ("ec-pipeline-collect", self._collect_loop)):
            t = threading.Thread(target=target, daemon=True, name=name)
            t.start()
            self._threads.append(t)

    def stop(self, timeout: float = 5.0) -> None:
        with self._lock:
            self._running = False
            self._work_cv.notify_all()
            self._inflight_cv.notify_all()
            self._fetch_cv.notify_all()
        for t in self._threads:
            t.join(timeout)
        self._threads.clear()

    def flush(self, timeout: float = 60.0) -> bool:
        """Block until every queued + in-flight item resolved."""
        end = time.monotonic() + timeout
        while time.monotonic() < end:
            with self._lock:
                if not self._inflight and not self._busy and \
                        not any(self._queues.values()):
                    return True
            time.sleep(0.005)
        return False

    # -- producer side -----------------------------------------------------

    def submit(self, chan: PipelineChannel, arr: np.ndarray) -> Future:
        """Queue a (B, ...) uint8 batch on `chan`.  The future resolves
        to (path, outputs) with path in {"dev", "host"} and outputs the
        channel fn's tuple, sliced to this submission's B rows."""
        arr = np.ascontiguousarray(arr, dtype=np.uint8)
        if arr.ndim < 1 or arr.shape[0] == 0:
            raise ValueError(f"empty pipeline submission {arr.shape}")
        item = _Item(arr)
        with self._lock:
            self._ensure_threads()
            self._chans[chan.key] = chan
            self._queues.setdefault(chan.key, deque()).append(item)
            self._c["ops"] += 1
            self._c["stripes"] += item.n
            qd = sum(len(q) for q in self._queues.values())
            if qd > self._c["max_queue_depth"]:
                self._c["max_queue_depth"] = qd
            self._work_cv.notify()
        return item.fut

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._c)
            out["queue_depth"] = sum(len(q) for q in
                                     self._queues.values())
            out["inflight"] = len(self._inflight)
            out["stalled"] = self._stalled
        out["depth"] = self.depth
        d = out["dispatches"]
        out["mean_batch_size"] = (out["stripes"] / d) if d else 0.0
        return out

    # -- dispatcher --------------------------------------------------------

    def _pick_key(self):
        """Channel holding the OLDEST queued item (FIFO across
        channels).  Fairness over batch-size greed: a scrub channel
        with hundreds of queued CRC batches must not starve a client
        write's single-stripe encode — coalescing still happens
        because the dispatch takes everything queued on the picked
        channel, and depth backpressure lets more accumulate."""
        best, best_t = None, None
        for key, q in self._queues.items():
            if q and (best_t is None or q[0].t < best_t):
                best, best_t = key, q[0].t
        return best

    def _dispatch_loop(self) -> None:
        while True:
            with self._lock:
                while self._running and \
                        not any(self._queues.values()):
                    self._work_cv.wait()
                if not self._running:
                    return
                # overlap cap: while `depth` device dispatches are in
                # flight, hold off — arrivals during the wait coalesce
                # into the next mega-batch (the whole point)
                waited = False
                wait_start = None
                while self._running and not self._stalled and \
                        len(self._inflight) >= self.depth:
                    waited = True
                    now = time.monotonic()
                    if wait_start is None:
                        wait_start = now
                    elif now - wait_start > STALL_TIMEOUT:
                        # the collector is wedged inside a hung device
                        # fetch (no exception to degrade on): latch
                        # host-only dispatch so EC I/O keeps flowing;
                        # producers stuck on the wedged dispatches
                        # self-serve via their RESULT_TIMEOUT
                        self._stalled = True
                        from ..utils.dout import DoutLogger
                        DoutLogger("ops", "ec-pipeline").warn(
                            "device fetch stalled > %.0fs with %d "
                            "dispatches in flight: latching pipeline "
                            "to host-only dispatch", STALL_TIMEOUT,
                            len(self._inflight))
                        break
                    self._fetch_cv.wait(self.coalesce_wait or 0.01)
                if waited:
                    self._c["coalesce_waits"] += 1
                if not self._running:
                    return
                key = self._pick_key()
                if key is None:
                    continue
                chan = self._chans[key]
                q = self._queues[key]
                cap = chan.max_coalesce or self.max_batch
                items, n = [], 0
                while q and (not items or n + q[0].n <= cap):
                    it = q.popleft()
                    items.append(it)
                    n += it.n
                if not q:
                    # self-cleaning registry: a drained key drops its
                    # queue AND channel ref (submit re-registers), so
                    # retired codecs / one-off decode patterns cannot
                    # accumulate in the process-wide singleton
                    del self._queues[key]
                    self._chans.pop(key, None)
                self._busy += 1
            try:
                self._dispatch(chan, items)
            except Exception as e:      # never kill the loop
                for it in items:
                    if not it.fut.done():
                        it.fut.set_exception(e)
            finally:
                with self._lock:
                    self._busy -= 1

    def _dispatch(self, chan: PipelineChannel, items: list) -> None:
        arrs = [it.arr for it in items]
        batch = arrs[0] if len(arrs) == 1 else np.concatenate(arrs)
        nbytes = batch.nbytes
        use_dev = False
        if chan.device_fn is not None and not self._stalled:
            try:
                use_dev = bool(chan.route(nbytes))
            except Exception:
                use_dev = False
        if use_dev:
            padded = pad_batch(batch)
            t0 = time.perf_counter()
            out = None
            try:
                out = chan.device_fn(padded)
            except Exception as e:
                with self._lock:
                    self._c["device_errors"] += 1
                    self._c["drained_to_host"] += len(items)
                chan.on_error(e)
            if out is not None:
                disp = _Dispatch(chan, items, batch.shape[0], out, t0,
                                 nbytes)
                with self._lock:
                    self._inflight.append(disp)
                    self._inflight_cv.notify()
                return
            # device not warm yet (None) or errored: fall through
        self._run_host(chan, items, batch)

    # -- collector ---------------------------------------------------------

    def _collect_loop(self) -> None:
        while True:
            with self._lock:
                while self._running and not self._inflight:
                    self._inflight_cv.wait()
                if not self._running:
                    return
                disp = self._inflight.popleft()
                self._busy += 1
            try:
                self._collect_one(disp)
            except Exception as e:
                # never kill the loop: a dead collector would leak
                # _busy and wedge every producer blocked in result()
                for it in disp.items:
                    if not it.fut.done():
                        it.fut.set_exception(e)
            finally:
                with self._lock:
                    self._busy -= 1
                    self._fetch_cv.notify_all()

    def _collect_one(self, disp: _Dispatch) -> None:
        try:
            outs = tuple(np.asarray(o) for o in disp.out)
            now = time.perf_counter()
            # marginal service time: overlap with the previous fetch
            # does not double-bill — this is the amortized sec/byte
            # the measured router scores
            start = max(disp.t0, self._last_fetch_done)
            self._last_fetch_done = now
            with self._lock:
                depth = len(self._inflight) + 1
                self._c["dispatches"] += 1
                self._c["dev_dispatches"] += 1
            try:
                disp.chan.record("dev", disp.nbytes,
                                 max(now - start, 1e-9), depth)
            except Exception:
                pass
            self._resolve(disp.items, "dev",
                          tuple(o[: disp.S] for o in outs))
        except Exception as e:
            # async-dispatch errors surface at fetch: degrade + re-run
            # the WHOLE batch on host — nothing queued is lost
            with self._lock:
                self._c["device_errors"] += 1
                self._c["drained_to_host"] += len(disp.items)
            disp.chan.on_error(e)
            arrs = [it.arr for it in disp.items]
            batch = arrs[0] if len(arrs) == 1 else np.concatenate(arrs)
            self._run_host(disp.chan, disp.items, batch)

    # -- shared ------------------------------------------------------------

    def _run_host(self, chan: PipelineChannel, items: list,
                  batch: np.ndarray) -> None:
        t0 = time.perf_counter()
        try:
            outs = tuple(np.asarray(o) for o in chan.host_fn(batch))
        except Exception as e:
            for it in items:
                if not it.fut.done():
                    it.fut.set_exception(e)
            return
        with self._lock:
            self._c["dispatches"] += 1
            self._c["host_dispatches"] += 1
        try:
            chan.record("host", batch.nbytes,
                        max(time.perf_counter() - t0, 1e-9), 1)
        except Exception:
            pass
        self._resolve(items, "host", outs)

    @staticmethod
    def _resolve(items: list, path: str, outs: tuple) -> None:
        off = 0
        for it in items:
            sl = tuple(o[off: off + it.n] for o in outs)
            off += it.n
            if not it.fut.done():
                it.fut.set_result((path, sl))


# ---------------------------------------------------------------------------
# Process-wide singleton (all producers in a process share one queue —
# that IS the cross-op coalescing) + plugin-agnostic channels.
# ---------------------------------------------------------------------------

_global: EcDevicePipeline | None = None
_glock = threading.Lock()


def get() -> EcDevicePipeline:
    global _global
    if _global is None:
        with _glock:
            if _global is None:
                _global = EcDevicePipeline()
    return _global


def configure(depth: int | None = None,
              coalesce_wait: float | None = None,
              max_batch: int | None = None) -> EcDevicePipeline:
    """Tune the shared pipeline (daemon startup applies its conf)."""
    p = get()
    if depth is not None:
        p.depth = max(1, int(depth))
    if coalesce_wait is not None:
        p.coalesce_wait = max(0.0, float(coalesce_wait))
    if max_batch is not None:
        p.max_batch = max(1, int(max_batch))
    return p


def stats() -> dict:
    return get().stats()


# -- deep-scrub CRC channels -------------------------------------------------
#
# Keyed per shard size; device fn is the jitted CRC fold, warmed on a
# background thread exactly like TpuBackend's codec fns so the shared
# dispatcher never blocks tens of seconds inside a first-shape compile.

_crc_channels: dict[int, PipelineChannel] = {}
# warmed jitted fns are pinned HERE, not re-fetched through
# ec_kernels' lru_cache: an LRU eviction would otherwise recompile
# inline on the shared dispatcher thread while the readiness set
# still claims the shape is warm (TpuBackend couples _fns/_ready the
# same way)
_crc_fns: dict = {}
_crc_ready: set = set()
_crc_warming: set = set()
_crc_warm_failed: set = set()
_crc_lock = threading.Lock()
# sticky device-dead latch (the tpu plugin's degrade equivalent): a
# REAL post-warm device failure must not cost a failing dispatch +
# host re-run on every later scrub batch until daemon restart
_crc_device_dead = False


def _crc_on_error(e: Exception) -> None:
    global _crc_device_dead
    if not _crc_device_dead:
        _crc_device_dead = True
        from ..utils.dout import DoutLogger
        DoutLogger("ops", "ec-pipeline").warn(
            "scrub CRC device path failed (%s: %s): latching to host "
            "fold", type(e).__name__, e)


def _crc_device_fn(size: int):
    def device_fn(padded: np.ndarray):
        key = (size, padded.shape)
        with _crc_lock:
            fn = _crc_fns.get(key)
            if fn is None:
                # negative-cache warm failures (TpuBackend does the
                # same): re-warming every dispatch would churn a
                # thread + a failing ~10s backend init per batch
                if key not in _crc_warming and \
                        key not in _crc_warm_failed:
                    _crc_warming.add(key)
                    threading.Thread(
                        target=_warm_crc, args=(size, padded.shape),
                        daemon=True, name="ec-crc-warm").start()
                return None
        return (fn(padded),)

    return device_fn


def _warm_crc(size: int, shape: tuple) -> None:
    from . import ec_kernels
    key = (size, shape)
    fn = None
    try:
        fn = ec_kernels.make_crc_fn(size)
        np.asarray(fn(np.zeros(shape, dtype=np.uint8)))
    except Exception:
        fn = None   # negative-cached below; host path keeps serving
    finally:
        with _crc_lock:
            _crc_warming.discard(key)
            if fn is not None:
                if len(_crc_fns) > 256:
                    _crc_fns.clear()
                    _crc_ready.clear()
                _crc_fns[key] = fn
                _crc_ready.add(key)
            else:
                _crc_warm_failed.add(key)


def crc_channel(size: int,
                max_coalesce: int | None = None) -> PipelineChannel:
    """Shared channel computing CRC32C(seed 0) per row of (B, size)
    batches; future outputs are ((B,) uint32,).  `max_coalesce`
    bounds stripes per dispatch (the scrubber passes its
    osd_deep_scrub_stripe_batch so coalescing cannot exceed the
    operator's per-dispatch device-memory cap)."""
    with _crc_lock:
        chan = _crc_channels.get(size)
        if chan is None:
            from . import crc32c as crc_mod
            from ..utils import faults

            def host_fn(batch):
                return (crc_mod.crc32c_batch(batch),)

            def route(nbytes):
                return not _crc_device_dead and \
                    not faults.get().tpu_error()

            chan = PipelineChannel(
                key=("crc", size), host_fn=host_fn,
                device_fn=_crc_device_fn(size), route=route,
                on_error=_crc_on_error, max_coalesce=max_coalesce)
            _crc_channels[size] = chan
        elif max_coalesce is not None:
            # several daemons share this in-process registry: honor
            # the STRICTEST per-dispatch cap any of them configured
            chan.max_coalesce = max_coalesce if chan.max_coalesce \
                is None else min(chan.max_coalesce, max_coalesce)
        return chan
