"""Cross-op EC device pipeline: coalesce stripe work, amortize dispatch,
spread mega-batches across every visible chip.

The kernels win by 5x (BENCH_r05: 30-50 GB/s vs ~6 GB/s host AVX2) but
the *op path* lost end-to-end: every EC write, scrub batch and rebuild
paid its own serial host->device->host round trip (~90 ms through the
axon tunnel) for a stripe batch worth ~1 ms of device time.  A storage
daemon has exactly the concurrency that amortizes a fixed dispatch
cost — many in-flight writes, scrub chunks and recovery rebuilds are
embarrassingly parallel stripes (SURVEY §5.7) — and the serial path
threw it away.

This module is the shared dispatcher all producers feed:

  * **channels** — a :class:`PipelineChannel` is one coalescable work
    class (same jitted kernel set): whole-object/append encodes of one
    (matrix, L), deep-scrub CRC folds of one shard size, rebuild
    decodes of one rows-matrix.  Items on one channel concatenate
    along the batch axis into a mega-batch.
  * **shape buckets** — mega-batches pad to a power-of-two stripe
    count (:func:`pad_batch`), so the device sees a small repeating
    shape set and jit recompiles stop after warm-up.
  * **device lanes** — a :class:`DeviceSet` enumerates every visible
    jax device at first use (``osd_ec_device_shards`` caps it); each
    device gets a dispatch lane with its OWN overlap window of
    ``depth`` in-flight dispatches and its own collector thread.
    Placement is least-loaded with a round-robin tie-break, so the
    aggregate window is ``depth * n_devices`` and one hot channel
    cannot serialize every producer behind one chip.
  * **mega-batch splitting** — a large coalesced batch additionally
    splits across idle lanes (``split_min`` stripes per shard, ceil
    partition): each shard pads to its own bucket, pins to its lane
    with ``jax.device_put``, and the parts re-assemble in submit
    order — bit-identical to the unsplit dispatch.
  * **futures** — :meth:`EcDevicePipeline.submit` returns a
    ``concurrent.futures.Future`` resolving to ``(path, outputs)``,
    so an OSD op submits its encode, keeps journaling metadata, and
    collects parity+CRCs at commit time.
  * **quarantine + redrain** — a device error on ONE chip (a real
    dispatch/fetch failure, or an injected ``tpu_error`` targeted at
    that device index) quarantines that lane only: the failed batch
    and everything queued redrains onto the surviving chips,
    bit-identically.  Only when EVERY lane is quarantined does the
    channel owner hear ``on_error`` (the tpu plugin degrades to the
    host matrix codec) and the queue drain to the host fn: no queued
    op is ever lost or corrupted, and one dead chip costs 1/n of the
    fleet, not all of it.
  * **scrub QoS** — under contention the deep-scrub CRC channels
    yield to client-write encode/decode channels:
    ``osd_ec_pipeline_scrub_weight`` bounds scrub's share of
    contended dispatch slots (weight w -> one pick in round(1/w)).

  * **zero-copy transfer plane** — each lane owns a STAGER thread and
    a double-buffered staging arena: the dispatcher hands a planned
    part to the lane and moves on immediately; the stager performs the
    H2D upload and issues the async compute, so batch N+1 uploads
    while batch N computes and uploads to different chips run in
    parallel instead of serializing on the dispatcher thread (the old
    per-dispatch synchronous ``device_put``).  Readback is
    parity-only: the fused kernel never echoes data shards, so per
    dispatch exactly ``S_pad * k * L`` bytes go up and
    ``S_pad * (m * L + 4 * (k + m))`` bytes come down — the
    ``bytes_h2d`` / ``bytes_d2h`` counters prove it (bench --smoke
    gates on the exact identity).
  * **HBM stripe cache** — an encode submission tagged with a
    :class:`~ceph_tpu.ops.hbm_cache.CacheIntent` leaves its uploaded
    data and computed parity ON the chip (device slices, no extra
    transfer): deep-scrub CRC folds and recovery decodes of that
    object then hit HBM with zero H2D (ceph_tpu.ops.hbm_cache).  A
    quarantined lane's entries drop with it.
  * **cost-aware placement** — each lane keeps per-shape-bucket EMAs
    of its marginal service time (the same samples
    ``TpuBackend.record`` scores, fed at fetch completion); when a
    measured slow chip would win the least-loaded tie, placement
    routes around it and ``cost_diverged`` counts how often the
    measured choice disagreed with least-loaded.
  * **mesh dispatch** — the pod-scale placement mode: ONE coalesced
    batch whose staged bytes exceed a single lane's budget
    (``osd_ec_mesh_min_bytes``) shard_maps across a device mesh built
    from the active lanes (``osd_ec_device_mesh`` picks the axis
    layout: "auto" = every active chip on one chunk-length axis,
    "AxB" = dp x ls) instead of splitting into independent per-lane
    row batches.  Parity is row-local in the chunk-length axis so the
    L-split needs no communication; scrub/chunk CRC partials combine
    ON device (XOR psum) before one small D2H fetch — this is what
    lets a batch bigger than one chip's HBM dispatch at all.  The
    quarantine ladder extends downward: a device fault inside a mesh
    dispatch degrades THAT batch to surviving-lane row splits (then
    host), bit-identically (``mesh_dispatches`` / ``mesh_degrades``).
  * **pinned staging arenas + donation** — mesh-sized encodes stage
    their payload into a reusable arena buffer
    (:meth:`EcDevicePipeline.checkout_arena`); on the mesh path the
    arena's device allocation is DONATED to the computation
    (``donate_argnums``), so the ``ec.stage`` staging copy *is* the
    H2D upload — the copy-audit site retires there
    (``arena_donations`` counts it) and re-arms automatically if the
    batch degrades to a non-mesh path.  An arena is never recycled
    while its dispatch (or the shard fan-out reading it) is in
    flight; release() returns it to the pool for the next mega-write.

Host batches run inline on the dispatcher thread — single-threaded
host execution is itself the coalescing backpressure: while one host
batch runs, new submissions queue and the next dispatch swallows them
all in one call.

Timing recorded per dispatch is the *marginal* service time per LANE
(now minus the later of dispatch-issue and that lane's previous
fetch-completion), so an overlapped device dispatch records its
amortized per-chip cost, not the full tunnel latency — that is what
makes the TpuBackend's measured host/device routing produce a finite
crossover, and it stays meaningful when n chips serve in parallel.
"""

from __future__ import annotations

import inspect
import threading
import time
from collections import deque
from concurrent.futures import Future

import numpy as np

from ..utils import copyaudit, faults
from . import hbm_cache

# defaults; daemons override via configure() from their conf
# (osd_ec_pipeline_depth / _coalesce_ms / _max_batch /
#  osd_ec_device_shards / osd_ec_pipeline_scrub_weight /
#  osd_ec_cost_aware_placement / osd_ec_hbm_cache_bytes /
#  osd_ec_mesh_min_bytes / osd_ec_device_mesh /
#  osd_qos_cost_bytes_unit)
DEFAULT_DEPTH = 2
DEFAULT_COALESCE_WAIT = 0.002
DEFAULT_MAX_BATCH = 256
DEFAULT_SPLIT_MIN = 4       # min stripes per per-chip shard of a split
DEFAULT_SCRUB_WEIGHT = 0.25
DEFAULT_COST_AWARE = True
# a single lane's staging budget: a coalesced batch larger than this
# cannot ride one chip's HBM and dispatches via the device mesh
DEFAULT_MESH_MIN_BYTES = 256 << 20
DEFAULT_DEVICE_MESH = "auto"
# dmClock cost normalization for the dispatch-lane tenant picker
# (mirrors the op queue's osd_qos_cost_bytes_unit; 0 = cost 1/pick)
DEFAULT_QOS_COST_UNIT = 4096
ARENA_POOL_MAX = 4          # free staging arenas kept for reuse
# a measured-cost pick must beat the least-loaded pick by this factor
# to override it: EMA noise alone must not starve a healthy lane of
# the rotation (unprobed lanes have no EMA and always keep their turn)
COST_MARGIN = 1.25

_UNSET = object()

# liveness bounds: a device fetch that HANGS (no exception) must not
# become a process-wide EC outage.  A lane whose collector sits inside
# one fetch longer than STALL_TIMEOUT is skipped by placement; when
# every usable lane's window has been full for STALL_TIMEOUT the
# dispatcher latches host-only dispatch; producers self-serve on host
# after RESULT_TIMEOUT blocked in result() (encode/CRC are pure
# functions of inputs they still hold, and the future's done() guard
# makes a late device resolution harmless).
STALL_TIMEOUT = 60.0
RESULT_TIMEOUT = 120.0


def next_bucket(n: int) -> int:
    """Power-of-two shape bucket for a batch of n stripes."""
    return 1 << (n - 1).bit_length() if n > 1 else 1


def pad_batch(batch: np.ndarray) -> np.ndarray:
    """Zero-pad axis 0 to the next power of two so device shapes
    repeat (jit is shape-specialized; a stable bucket set compiles
    once per size).  Callers slice the result back to the true count;
    host paths never pay the padding."""
    S = batch.shape[0]
    S_pad = next_bucket(S)
    if S_pad == S:
        return batch
    return np.concatenate(
        [batch, np.zeros((S_pad - S,) + batch.shape[1:], dtype=np.uint8)])


def _wrap_device_fn(device_fn):
    """Channels predate device placement; accept both fn(padded) and
    fn(padded, device).  Wrapping once at construction keeps the
    dispatch path free of per-call signature probing."""
    if device_fn is None:
        return None
    try:
        params = list(inspect.signature(device_fn).parameters.values())
    except (TypeError, ValueError):
        return device_fn
    if len(params) >= 2 or any(
            p.kind in (p.VAR_POSITIONAL, p.VAR_KEYWORD) for p in params):
        return device_fn

    def wrapped(padded, device=None, _fn=device_fn):
        return _fn(padded)

    return wrapped


def _wrap_record(record):
    """Like :func:`_wrap_device_fn` for the record callback: newer
    owners take a ``device=`` kwarg (per-(shape, chip) routing EMAs in
    TpuBackend.record); legacy four-argument callbacks are wrapped so
    the dispatch path stays free of per-call signature probing."""
    if record is None:
        return lambda path, nbytes, secs, depth=1, device=None: None
    try:
        params = inspect.signature(record).parameters
    except (TypeError, ValueError):
        return record
    if "device" in params or any(
            p.kind == p.VAR_KEYWORD for p in params.values()):
        return record

    def wrapped(path, nbytes, secs, depth=1, device=None, _fn=record):
        return _fn(path, nbytes, secs, depth)

    return wrapped


class PipelineChannel:
    """One coalescable work class.

    host_fn(batch) -> tuple of np arrays, each with leading dim ==
    batch.shape[0].  device_fn(padded_batch, device) -> same tuple of
    (lazy) device arrays, or None when the jitted fn is not warm yet
    on that device (the batch then runs on host while a background
    compile proceeds); legacy single-argument device_fns are wrapped.
    route(nbytes) -> True to try the device for a coalesced batch of
    that size.  on_error(exc) fires when the device path is exhausted
    (every lane quarantined — the tpu plugin degrades there);
    record(path, nbytes, secs, depth) feeds the owner's
    measured-routing EMA.  qos_class "scrub" marks channels that
    yield to "write" channels under contention.

    mesh_fn(batch, plane, donate=False, keep_resident=False) is the
    optional pod-scale entry: serve one whole batch sharded across
    `plane`'s device mesh, returning (outputs, resident) — outputs
    bit-identical to host_fn(batch), resident the device arrays for
    the HBM cache or None — or None while the mesh kernel is still
    compiling (the batch then row-splits or host-serves).
    """

    __slots__ = ("key", "host_fn", "device_fn", "route", "on_error",
                 "record", "max_coalesce", "qos_class", "mesh_fn")

    def __init__(self, key, host_fn, device_fn=None, route=None,
                 on_error=None, record=None, max_coalesce=None,
                 qos_class="write", mesh_fn=None):
        self.key = key
        self.host_fn = host_fn
        self.device_fn = _wrap_device_fn(device_fn)
        self.route = route if route is not None else \
            (lambda nbytes: device_fn is not None)
        self.on_error = on_error or (lambda e: None)
        self.record = _wrap_record(record)
        self.max_coalesce = max_coalesce
        self.qos_class = qos_class
        self.mesh_fn = mesh_fn


class StagingArena:
    """One reusable (pinned, on a real rig) staging buffer: the
    producer copies its payload rope straight into `buf`, the mesh
    dispatch uploads FROM it with the device allocation donated to
    the computation — so the staging copy and the H2D transfer are
    one move, and the audited ``ec.stage`` site retires on that path.

    Lifecycle: :meth:`EcDevicePipeline.checkout_arena` hands out a
    zeroed buffer that is NOT in the free pool (a concurrent
    submission always gets a fresh arena); the last reader — the
    shard fan-out that lays shards out of the staged stripes — calls
    :meth:`release` to return it.  ``consumed`` latches once a mesh
    dispatch donated/uploaded it (the pipeline never reads it again);
    a batch that degrades to a non-mesh path instead notes the
    staging copy under ``ec.stage`` at resolve time, so the copy
    audit stays honest on every rung of the ladder."""

    __slots__ = ("buf", "payload_bytes", "consumed", "noted", "_pool")

    def __init__(self, buf: np.ndarray, payload_bytes: int, pool):
        self.buf = buf
        self.payload_bytes = int(payload_bytes)
        self.consumed = False
        self.noted = False
        self._pool = pool

    def release(self) -> None:
        pool, self._pool = self._pool, None
        if pool is None:
            return
        if self.consumed or self.noted:
            pool._return_arena(self)
        else:
            # neither flag set means the pipeline never RESOLVED this
            # arena's item — the producer self-served around a wedged
            # dispatch (RESULT_TIMEOUT) and the queued item still
            # views buf.  Recycling it would let a new checkout zero
            # the buffer under that live reader; drop it instead (the
            # item's view keeps the memory alive, it just never
            # re-enters the pool).
            self.buf = None


class _MeshPlane:
    """The dp x ls device mesh a pod-scale dispatch shard_maps over:
    a snapshot of the active lanes at build time.  Invalidated when
    any member lane quarantines or the device set rebuilds."""

    __slots__ = ("lanes", "lane_indices", "devices", "n_dp", "n_ls")

    def __init__(self, lanes: list, n_dp: int, n_ls: int):
        self.lanes = lanes
        self.lane_indices = tuple(l.index for l in lanes)
        self.devices = tuple(l.device for l in lanes)
        self.n_dp = n_dp
        self.n_ls = n_ls

    def key(self) -> tuple:
        return (self.devices, self.n_dp, self.n_ls)


class _Item:
    __slots__ = ("arr", "n", "fut", "t", "cache", "tag", "arena",
                 "no_mesh", "ph")

    def __init__(self, arr: np.ndarray, cache=None, tag=None,
                 arena=None):
        self.arr = arr
        self.n = arr.shape[0]
        self.fut: Future = Future()
        self.t = time.monotonic()
        self.cache = cache          # hbm_cache.CacheIntent | None
        self.tag = tag              # QoS service class (pool name)
        self.arena = arena          # StagingArena | None
        self.no_mesh = False        # degrade latch: never re-mesh
        # op-tracing phase stamps (time.monotonic — the span
        # timebase): submit -> picked (coalesce wait) -> stage0/1
        # (H2D) -> issue -> collect0 (compute done) -> done (D2H), or
        # host0/host1 for the host drain; requeues counts degrades.
        # Attached to the future as `trace_phases` at resolve so the
        # producer's op thread can span its TrackedOp.
        self.ph: dict = {"submit": self.t}


class _Lane:
    """One device's dispatch lane: its own overlap window (a deque of
    in-flight dispatches bounded by the pipeline depth), a stager
    thread + staging queue (the double-buffered H2D arena: upload of
    batch N+1 proceeds while batch N computes, and uploads to
    different chips run in parallel), its own collector thread,
    transfer accounting, and per-shape-bucket marginal service-time
    EMAs for cost-aware placement."""

    __slots__ = ("device", "index", "inflight", "stage_q", "staging",
                 "quarantined", "quarantine_reason", "alive",
                 "collect_started", "stage_started", "last_fetch_done",
                 "dispatches", "stripes", "nbytes", "errors",
                 "bytes_h2d", "bytes_d2h", "spb")

    def __init__(self, device, index: int):
        self.device = device
        self.index = index
        self.inflight: deque = deque()
        self.stage_q: deque = deque()
        self.staging = 0             # parts popped, not yet in flight
        self.quarantined = False
        self.quarantine_reason = ""
        self.alive = True            # False once the devset is rebuilt
        self.collect_started: float | None = None
        self.stage_started: float | None = None
        self.last_fetch_done = 0.0
        self.dispatches = 0
        self.stripes = 0
        self.nbytes = 0
        self.errors = 0
        self.bytes_h2d = 0
        self.bytes_d2h = 0
        # shape-bucket (power of two of part bytes) -> marginal
        # sec/byte EMA — the same samples TpuBackend.record scores,
        # kept per chip so placement can prefer a measured-faster lane
        self.spb: dict[int, dict] = {}

    def load(self) -> int:
        """Occupancy the overlap window bounds: dispatched + staged +
        mid-staging parts (a part being uploaded is claimed work)."""
        return len(self.inflight) + len(self.stage_q) + self.staging

    def note_service(self, nbytes: int, secs: float) -> None:
        b = (max(nbytes, 1) - 1).bit_length()
        ent = self.spb.setdefault(b, {"spb": None, "n": 0})
        ent["n"] += 1
        spb = secs / max(nbytes, 1)
        ent["spb"] = spb if ent["spb"] is None else (
            0.7 * ent["spb"] + 0.3 * spb)

    def predict(self, nbytes: int) -> float | None:
        """Predicted marginal seconds to serve nbytes more on this
        lane (None until the shape bucket has enough samples)."""
        ent = self.spb.get((max(nbytes, 1) - 1).bit_length())
        if ent is None or ent["n"] < 3 or ent["spb"] is None:
            return None
        return ent["spb"] * nbytes * (self.load() + 1)

    def stuck(self, now: float) -> bool:
        for started in (self.collect_started, self.stage_started):
            if started is not None and now - started > STALL_TIMEOUT:
                return True
        return False

    def dump(self) -> dict:
        return {"device": str(self.device) if self.device is not None
                else "default",
                "dispatches": self.dispatches, "stripes": self.stripes,
                "bytes": self.nbytes, "errors": self.errors,
                "inflight": len(self.inflight),
                "staged": len(self.stage_q) + self.staging,
                "bytes_h2d": self.bytes_h2d,
                "bytes_d2h": self.bytes_d2h,
                "quarantined": self.quarantined,
                "quarantine_reason": self.quarantine_reason}


class DeviceSet:
    """The visible device topology, enumerated once at first device
    dispatch (importing jax is not free; host-only processes never
    pay it).  `shards` caps how many devices the pipeline spreads
    over (conf osd_ec_device_shards; None = all)."""

    def __init__(self, shards: int | None = None):
        devices: list = []
        try:
            import jax
            devices = list(jax.devices())
        except Exception:
            devices = []
        if shards is not None:
            devices = devices[: max(1, int(shards))]
        if not devices:
            # no jax / no devices: one pseudo-lane keeps the dispatch
            # machinery uniform (device_fns get device=None, arrays
            # stay host-side)
            devices = [None]
        self.lanes = [_Lane(d, i) for i, d in enumerate(devices)]

    def active(self) -> list:
        return [l for l in self.lanes if not l.quarantined]


class _Group:
    """One mega-batch split across lanes: parts collect independently
    (possibly on different collector threads) and the futures resolve
    once every part landed, in original row order.  A failed part
    marks the whole group failed; its items requeue exactly once and
    surviving parts' outputs are discarded."""

    __slots__ = ("chan", "items", "nparts", "pending", "outs",
                 "failed", "nbytes", "t0")

    def __init__(self, chan, items, nparts, nbytes, t0):
        self.chan = chan
        self.items = items
        self.nparts = nparts
        self.pending = nparts
        self.outs: dict[int, tuple] = {}
        self.failed = False
        self.nbytes = nbytes
        self.t0 = t0


class _Staged:
    """One planned part waiting on (or inside) its lane's stager: the
    H2D upload + async compute issue happen on the lane's stager
    thread, off the dispatcher."""

    __slots__ = ("chan", "items", "part", "S", "group", "gidx")

    def __init__(self, chan, items, part, S, group=None, gidx=0):
        self.chan = chan
        self.items = items          # [] for split-group parts
        self.part = part
        self.S = S
        self.group = group
        self.gidx = gidx


class _Dispatch:
    __slots__ = ("chan", "items", "S", "out", "t0", "nbytes", "lane",
                 "group", "gidx", "dev_in")

    def __init__(self, chan, items, S, out, t0, nbytes, lane,
                 group=None, gidx=0, dev_in=None):
        self.chan = chan
        self.items = items
        self.S = S
        self.out = out
        self.t0 = t0
        self.nbytes = nbytes
        self.lane = lane
        self.group = group
        self.gidx = gidx
        self.dev_in = dev_in        # device-resident input (HBM cache)


def _cat_items(items: list) -> np.ndarray:
    """Reassemble one contiguous batch from items' stripe arrays."""
    arrs = [it.arr for it in items]
    return arrs[0] if len(arrs) == 1 else np.concatenate(arrs)


class EcDevicePipeline:
    def __init__(self, depth: int = DEFAULT_DEPTH,
                 coalesce_wait: float = DEFAULT_COALESCE_WAIT,
                 max_batch: int = DEFAULT_MAX_BATCH,
                 device_shards: int | None = None,
                 split_min: int = DEFAULT_SPLIT_MIN,
                 scrub_weight: float = DEFAULT_SCRUB_WEIGHT,
                 cost_aware: bool = DEFAULT_COST_AWARE,
                 mesh_min_bytes: int = DEFAULT_MESH_MIN_BYTES,
                 device_mesh: str = DEFAULT_DEVICE_MESH,
                 qos_cost_unit: int = DEFAULT_QOS_COST_UNIT):
        self.depth = max(1, int(depth))
        self.coalesce_wait = float(coalesce_wait)
        self.max_batch = max(1, int(max_batch))
        self.device_shards = device_shards
        self.split_min = max(1, int(split_min))
        self.scrub_weight = float(scrub_weight)
        self.cost_aware = bool(cost_aware)
        self.mesh_min_bytes = int(mesh_min_bytes)
        self.device_mesh = str(device_mesh)
        self.qos_cost_unit = max(0, int(qos_cost_unit))
        self._mesh: _MeshPlane | None = None
        self._arena_lock = threading.Lock()
        self._arena_free: list[np.ndarray] = []
        self._lock = threading.Lock()
        # three predicates, one lock: queued work (dispatcher waits),
        # in-flight dispatches (lane collectors wait), freed overlap
        # slots (dispatcher waits).  Separate conditions so a notify
        # can never wake the wrong thread and strand the right one.
        self._work_cv = threading.Condition(self._lock)
        self._inflight_cv = threading.Condition(self._lock)
        self._fetch_cv = threading.Condition(self._lock)
        # queues are keyed (chan.key, qos_tag): one coalescing stream
        # per (work class, tenant) — a mega-batch never mixes tenants,
        # so a reserved pool's encode can never wait INSIDE a noisy
        # pool's dispatch, and the picker below can order across
        # tenants (dmClock tags shared with the OSD op queue's conf)
        self._queues: dict = {}        # (chan.key, tag) -> deque[_Item]
        self._chans: dict = {}             # chan.key -> PipelineChannel
        from ..utils.dmclock import DmClockState
        self._qos = DmClockState()
        self._qos_enabled = False
        self._qos_wake = 0.0
        self._devset: DeviceSet | None = None
        self._rr = 0                       # placement tie-break rotor
        self._qos_contended = 0            # contended-pick counters
        self._qos_scrub = 0
        self._busy = 0                     # dispatches being processed
        self._stalled = False              # collectors wedged: host-only
        self._running = False
        self._threads: list = []
        self._c = {
            "dispatches": 0, "dev_dispatches": 0, "host_dispatches": 0,
            "ops": 0, "stripes": 0, "coalesce_waits": 0,
            "device_errors": 0, "drained_to_host": 0,
            "max_queue_depth": 0, "quarantines": 0,
            "split_dispatches": 0, "redrained": 0,
            "qos_scrub_yields": 0, "qos_cost_picks": 0,
            "bytes_h2d": 0, "bytes_d2h": 0,
            "cost_placements": 0, "cost_diverged": 0,
            "mesh_dispatches": 0, "mesh_degrades": 0,
            "arena_donations": 0,
        }

    # -- lifecycle ---------------------------------------------------------

    def _ensure_threads(self) -> None:
        if self._running:
            return
        self._running = True
        t = threading.Thread(target=self._dispatch_loop, daemon=True,
                             name="ec-pipeline-dispatch")
        t.start()
        self._threads.append(t)

    def _ensure_devset(self) -> DeviceSet:
        """Build the device set lazily (dispatcher thread only —
        imports jax, which must not run under the pipeline lock)."""
        ds = self._devset
        if ds is not None:
            return ds
        ds = DeviceSet(self.device_shards)
        with self._lock:
            if self._devset is None:
                self._devset = ds
                # collectors of retired device sets have exited by
                # now; drop them so repeated reset_devices sweeps
                # (bench chip-count sweep) cannot grow this unbounded
                self._threads = [t for t in self._threads
                                 if t.is_alive()]
                for lane in ds.lanes:
                    for target, tag in ((self._collect_loop, "collect"),
                                        (self._stage_loop, "stage")):
                        t = threading.Thread(
                            target=target, args=(lane,), daemon=True,
                            name=f"ec-pipeline-{tag}-{lane.index}")
                        t.start()
                        self._threads.append(t)
            return self._devset

    def reset_devices(self, device_shards=_UNSET) -> None:
        """Rebuild the device set on next dispatch: clears quarantine
        latches and (optionally) re-caps the shard count — bench's
        chip-count sweep and tests that quarantined lanes use this."""
        self.flush(timeout=10.0)
        with self._lock:
            if device_shards is not _UNSET:
                self.device_shards = device_shards
            ds, self._devset = self._devset, None
            if ds is not None:
                for lane in ds.lanes:
                    lane.alive = False
            self._mesh = None
            self._stalled = False
            self._inflight_cv.notify_all()
        # lane indices renumber with the topology: entries pinned to
        # the old lanes are no longer attributable — drop them (the
        # next writes repopulate from fresh uploads)
        hbm_cache.get().clear()

    def stop(self, timeout: float = 5.0) -> None:
        with self._lock:
            self._running = False
            # drop the device set: a restarted pipeline (submit after
            # stop) must rebuild it so fresh collector threads spawn —
            # reusing the old lanes would enqueue work nothing collects
            ds, self._devset = self._devset, None
            if ds is not None:
                for lane in ds.lanes:
                    lane.alive = False
            self._mesh = None
            self._work_cv.notify_all()
            self._inflight_cv.notify_all()
            self._fetch_cv.notify_all()
        for t in self._threads:
            t.join(timeout)
        self._threads.clear()
        hbm_cache.get().clear()

    def flush(self, timeout: float = 60.0) -> bool:
        """Block until every queued + staged + in-flight item resolved."""
        end = time.monotonic() + timeout
        while time.monotonic() < end:
            with self._lock:
                ds = self._devset
                inflight = sum(l.load() for l in ds.lanes) \
                    if ds else 0
                if not inflight and not self._busy and \
                        not any(self._queues.values()):
                    return True
            time.sleep(0.005)
        return False

    # -- producer side -----------------------------------------------------

    def checkout_arena(self, nbytes: int,
                       payload_bytes: int | None = None):
        """A staging arena for a mesh-sized encode, or None when the
        batch is under the lane budget (the caller then stages into a
        plain buffer and the classic ``ec.stage`` accounting applies).
        Exclusively owned until release(); concurrent checkouts never
        share a buffer.  The stripe-padding TAIL (everything past
        `payload_bytes`) comes back zeroed; the first `payload_bytes`
        are the caller's to overwrite entirely — a pooled reuse must
        not pay a full multi-hundred-MiB memset on the hot staging
        path when the payload copy-in immediately rewrites it."""
        if self.mesh_min_bytes <= 0 or nbytes < self.mesh_min_bytes:
            return None
        zero_from = 0 if payload_bytes is None \
            else min(int(payload_bytes), nbytes)
        buf = None
        with self._arena_lock:
            for i, b in enumerate(self._arena_free):
                if b.nbytes == nbytes:
                    buf = self._arena_free.pop(i)
                    break
        if buf is None:
            buf = np.zeros(nbytes, dtype=np.uint8)
        elif zero_from < nbytes:
            buf[zero_from:] = 0
        return StagingArena(
            buf, payload_bytes if payload_bytes is not None
            else nbytes, self)

    def _return_arena(self, arena: StagingArena) -> None:
        buf, arena.buf = arena.buf, None
        if buf is None:
            return
        with self._arena_lock:
            if len(self._arena_free) < ARENA_POOL_MAX:
                self._arena_free.append(buf)

    def submit(self, chan: PipelineChannel, arr: np.ndarray,
               cache=None, qos: str | None = None,
               arena=None) -> Future:
        """Queue a (B, ...) uint8 batch on `chan`.  The future resolves
        to (path, outputs) with path in {"dev", "host"} and outputs the
        channel fn's tuple, sliced to this submission's B rows.

        `cache` (an hbm_cache.CacheIntent) asks the plane to keep this
        submission's device-resident inputs/outputs in the HBM stripe
        cache when the dispatch runs on a device (encode channels
        only — the fn's outputs must be (parity, crcs)).

        `qos` names the submission's service class (the pool, for
        client-write encodes): work of one class coalesces together
        and the dispatcher's picks honor the class's dmClock tags
        (configure_qos) — dispatch-level reservation/weight/limit, so
        a tenant saturating encodes cannot monopolize the lanes.

        `arena` (a StagingArena the submission's stripes were staged
        into) marks the batch for donated mesh upload; a non-mesh
        serve re-arms the ``ec.stage`` copy accounting instead."""
        arr = np.ascontiguousarray(arr, dtype=np.uint8)
        if arr.ndim < 1 or arr.shape[0] == 0:
            raise ValueError(f"empty pipeline submission {arr.shape}")
        item = _Item(arr, cache=cache, tag=qos, arena=arena)
        with self._lock:
            self._ensure_threads()
            self._chans[chan.key] = chan
            self._queues.setdefault((chan.key, qos),
                                    deque()).append(item)
            self._c["ops"] += 1
            self._c["stripes"] += item.n
            qd = sum(len(q) for q in self._queues.values())
            if qd > self._c["max_queue_depth"]:
                self._c["max_queue_depth"] = qd
            self._work_cv.notify()
        return item.fut

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._c)
            out["queue_depth"] = sum(len(q) for q in
                                     self._queues.values())
            ds = self._devset
            out["inflight"] = sum(len(l.inflight) for l in ds.lanes) \
                if ds else 0
            out["staged"] = sum(len(l.stage_q) + l.staging
                                for l in ds.lanes) if ds else 0
            out["stalled"] = self._stalled
            out["devices"] = {str(l.index): l.dump()
                              for l in ds.lanes} if ds else {}
            out["active_devices"] = len(ds.active()) if ds else 0
            mp = self._mesh
            # per-axis device table: which lanes the pod-scale plane
            # spans and how the dp x ls axes map onto them
            out["mesh"] = ({"dp": mp.n_dp, "ls": mp.n_ls,
                            "lanes": list(mp.lane_indices),
                            "devices": [str(d) for d in mp.devices]}
                           if mp is not None else None)
        out["depth"] = self.depth
        out["device_shards"] = self.device_shards or "all"
        out["scrub_weight"] = self.scrub_weight
        out["cost_aware"] = self.cost_aware
        out["mesh_min_bytes"] = self.mesh_min_bytes
        out["device_mesh"] = self.device_mesh
        out["qos_cost_unit"] = self.qos_cost_unit
        d = out["dispatches"]
        out["mean_batch_size"] = (out["stripes"] / d) if d else 0.0
        # HBM stripe cache counters ride the same perf-dump section
        # (the cache is part of the transfer plane)
        for k, v in hbm_cache.stats().items():
            out[f"cache_{k}"] = v
        return out

    # -- dispatcher --------------------------------------------------------

    def _pick_key(self):
        """The (channel, tenant) queue to dispatch next.

        Two levels.  CLASS arbitration (unchanged from PR 3): the
        oldest queued item per class wins FIFO, except scrub yields to
        client-write work under contention (scrub_weight bounds its
        share of contended picks).  TENANT arbitration (per-pool QoS):
        among the write-class queue heads, a dmClock pick over the
        tenants' reservation/weight/limit tags (configure_qos) chooses
        WHICH tenant's stream dispatches — oldest-first within the
        tenant, exact cross-queue FIFO when no pool class is
        configured.  A write class fully limit-throttled serves scrub;
        with nothing else eligible the dispatcher sleeps till the
        earliest tag (self._qos_wake), never spinning and never
        serving a limited tenant above its cap."""
        best_w = best_s = None
        t_w = t_s = None
        write_heads: dict = {}
        for key, q in self._queues.items():
            if not q:
                continue
            chan = self._chans.get(key[0])
            if chan is not None and chan.qos_class == "scrub":
                if t_s is None or q[0].t < t_s:
                    best_s, t_s = key, q[0].t
            else:
                write_heads[key] = q[0].t
                if t_w is None or q[0].t < t_w:
                    best_w, t_w = key, q[0].t
        want = None
        if best_s is None:
            want = "write"
        elif best_w is None:
            return best_s
        else:
            w = self.scrub_weight
            if w >= 1.0:
                want = "scrub" if t_s < t_w else "write"
            else:
                # ratio-faithful: scrub's served fraction of contended
                # picks tracks the configured weight exactly
                self._qos_contended += 1
                if self._qos_scrub + 1 <= w * self._qos_contended:
                    self._qos_scrub += 1
                    want = "scrub"
                else:
                    if t_s < t_w:
                        self._c["qos_scrub_yields"] += 1
                    want = "write"
        if want == "scrub":
            return best_s
        if best_w is None:
            return None
        if not self._qos_enabled:
            return best_w
        return self._qos_pick_write(write_heads, best_s)

    def _qos_pick_write(self, write_heads: dict, best_s):
        """dmClock tenant pick among the write-class heads; falls back
        to scrub when every tenant is limit-throttled.

        Picks are BYTES-WEIGHTED: each candidate tenant's grant is
        charged 1 + head_batch_bytes/qos_cost_unit (the same
        normalization as the op queue's osd_qos_cost_bytes_unit), so
        a tenant streaming mega-batch encodes advances its tags
        proportionally further than one trickling 4 KiB stripes —
        configured rates meter bytes through the lanes, not dispatch
        counts (cost=1 was the PR 10 follow-up this closes)."""
        cands: dict = {}
        by_tag: dict = {}
        for key, t in write_heads.items():
            tag = key[1] if key[1] is not None else "_system"
            if t < cands.get(tag, float("inf")):
                cands[tag] = t
            by_tag.setdefault(tag, []).append((t, key))
        costs = None
        if self.qos_cost_unit > 0:
            costs = {}
            for tag, lst in by_tag.items():
                _t, hkey = min(lst, key=lambda e: e[0])
                head = self._queues[hkey][0]
                costs[tag] = 1.0 + head.arr.nbytes / self.qos_cost_unit
        client, _phase, wake = self._qos.pick(cands, costs=costs)
        if client is None:
            # every queued tenant over its limit: scrub may run; else
            # the dispatch loop sleeps until the earliest tag
            self._qos.note_stall()
            self._qos_wake = wake
            if best_s is not None and self.scrub_weight < 1.0:
                # scrub actually takes this contended pick: credit
                # the ratio ledger, or throttle windows would bank
                # scrub a burst of extra picks against resumed
                # client writes (the PR 3 share must stay honest)
                self._qos_scrub += 1
            return best_s
        if costs is not None:
            self._c["qos_cost_picks"] += 1
        return min(by_tag[client], key=lambda e: e[0])[1]

    def _window_full_locked(self, now: float) -> bool:
        """True while every usable lane's overlap window is full —
        the dispatcher holds off so arrivals coalesce into the next
        mega-batch (the whole point).  Quarantined and stuck lanes
        don't count: work must not wait behind a dead chip."""
        ds = self._devset
        if ds is None:
            return False
        lanes = [l for l in ds.lanes
                 if not l.quarantined and not l.stuck(now)]
        if not lanes:
            return False
        return all(l.load() >= self.depth for l in lanes)

    def _dispatch_loop(self) -> None:
        while True:
            with self._lock:
                while self._running and \
                        not any(self._queues.values()):
                    self._work_cv.wait()
                if not self._running:
                    return
                # overlap cap: while every lane's window is full, hold
                # off — arrivals during the wait coalesce into the
                # next mega-batch
                waited = False
                wait_start = None
                while self._running and not self._stalled and \
                        self._window_full_locked(time.monotonic()):
                    waited = True
                    now = time.monotonic()
                    if wait_start is None:
                        wait_start = now
                    elif now - wait_start > STALL_TIMEOUT:
                        # every usable lane's collector is wedged
                        # inside a hung device fetch (no exception to
                        # quarantine on): latch host-only dispatch so
                        # EC I/O keeps flowing; producers stuck on the
                        # wedged dispatches self-serve via their
                        # RESULT_TIMEOUT
                        self._stalled = True
                        from ..utils.dout import DoutLogger
                        DoutLogger("ops", "ec-pipeline").warn(
                            "device fetches stalled > %.0fs on every "
                            "usable lane: latching pipeline to "
                            "host-only dispatch", STALL_TIMEOUT)
                        break
                    self._fetch_cv.wait(self.coalesce_wait or 0.01)
                if waited:
                    self._c["coalesce_waits"] += 1
                if not self._running:
                    return
                key = self._pick_key()
                if key is None:
                    if any(self._queues.values()):
                        # work queued but every tenant limit-throttled:
                        # sleep until the earliest tag comes due (new
                        # submissions still notify immediately)
                        self._work_cv.wait(max(
                            0.001,
                            min(self._qos_wake - time.monotonic(),
                                0.1)))
                    continue
                chan = self._chans[key[0]]
                q = self._queues[key]
                cap = chan.max_coalesce or self.max_batch
                items, n = [], 0
                pick_t = time.monotonic()
                while q and (not items or n + q[0].n <= cap):
                    it = q.popleft()
                    it.ph["picked"] = pick_t    # coalesce wait ends
                    items.append(it)
                    n += it.n
                if not q:
                    # self-cleaning registry: a drained key drops its
                    # queue — and the channel ref once no other
                    # tenant's queue still needs it (submit
                    # re-registers), so retired codecs / one-off
                    # decode patterns cannot accumulate in the
                    # process-wide singleton
                    del self._queues[key]
                    if not any(k[0] == key[0] for k in self._queues):
                        self._chans.pop(key[0], None)
                self._busy += 1
            try:
                self._dispatch(chan, items)
            except Exception as e:      # never kill the loop
                for it in items:
                    if not it.fut.done():
                        it.fut.set_exception(e)
            finally:
                with self._lock:
                    self._busy -= 1

    # -- placement ---------------------------------------------------------

    def _quarantine_locked(self, lane: _Lane, reason: str) -> None:
        if lane.quarantined:
            return
        lane.quarantined = True
        lane.quarantine_reason = reason
        self._c["quarantines"] += 1
        # a mesh plane spanning this chip is gone with it: later
        # mega-batches rebuild from the survivors
        if self._mesh is not None and \
                lane.index in self._mesh.lane_indices:
            self._mesh = None
        # the chip is in an unknown state: its HBM cache entries must
        # never serve again (redrain re-uploads from host)
        hbm_cache.get().drop_lane(lane.index)

    def _log_quarantine(self, lane: _Lane, active_left: int) -> None:
        from ..utils.dout import DoutLogger
        DoutLogger("ops", "ec-pipeline").warn(
            "EC device lane %d (%s) quarantined (%s): redraining its "
            "work onto %d surviving chip(s)%s", lane.index,
            lane.device, lane.quarantine_reason, active_left,
            "" if active_left else " — none left, host fallback")

    def _plan_locked(self, S: int, nbytes: int = 0,
                     bounds: list | None = None) -> tuple[list, bool]:
        """Place a coalesced S-stripe batch: (plan, exhausted).

        plan is [(lane, row_start, row_count), ...] — one entry for a
        whole-batch dispatch, several when the batch splits across
        idle lanes; empty when no lane can take it right now.
        exhausted=True means every lane is quarantined (host fallback,
        channel owner gets on_error).  Injected per-device faults
        (``tpu_error <prob> <device>``) are rolled here, at placement,
        so a targeted fault quarantines its lane even before the
        jitted fn warmed on it.

        `bounds` (interior item-boundary row offsets, ascending) marks
        a CACHE-TAGGED batch: splits may only cut at item boundaries,
        so every tagged item's rows land whole on ONE chip and its
        stripes can stay in that chip's HBM cache (a row-split part
        can't stage — an item's rows would straddle lanes).  A
        single-item tagged batch therefore rides whole on one lane:
        HBM residency saves the scrub/recovery re-upload AND the
        recompute, which beats one parallel upload.

        Whole-batch picks are COST-AWARE: per-(shape-bucket, chip)
        marginal service-time EMAs (fed from the same samples
        TpuBackend.record scores) override the least-loaded choice
        when a measured-faster lane would beat it by COST_MARGIN —
        `cost_diverged` counts the overrides.  Lanes without samples
        keep their least-loaded/round-robin turn, so every chip stays
        probed.
        """
        ds = self._devset
        if ds is None:
            # rebuilding (reset_devices raced this dispatch): host
            # serves this batch; the fresh device set takes the next
            return [], False
        now = time.monotonic()
        fs = faults.get()
        cands = []
        for lane in ds.lanes:
            if lane.quarantined or lane.stuck(now):
                continue
            if fs.tpu_error(device=lane.index):
                self._quarantine_locked(lane, "injected device error")
                self._c["device_errors"] += 1
                lane.errors += 1
                continue
        # re-scan after the fault roll (it may have quarantined lanes)
        active = ds.active()
        if not active:
            return [], True
        for lane in active:
            if not lane.stuck(now) and lane.load() < self.depth:
                cands.append(lane)
        if not cands:
            if all(lane.stuck(now) for lane in active):
                # every surviving chip's collector is wedged inside a
                # hung fetch: latch host-only dispatch (same terminal
                # state the window-full wait reaches) so placement
                # stops probing dead lanes per batch
                self._stalled = True
                from ..utils.dout import DoutLogger
                DoutLogger("ops", "ec-pipeline").warn(
                    "all %d active EC device lanes stuck > %.0fs: "
                    "latching pipeline to host-only dispatch",
                    len(active), STALL_TIMEOUT)
            return [], False
        n = len(cands)
        rot = self._rr
        self._rr += 1
        cands.sort(key=lambda l: (l.load(), (l.index - rot) % n))
        idle = [l for l in cands if not l.load()]
        nparts = min(len(idle), S // self.split_min)
        if nparts >= 2:
            if bounds is not None:
                cuts = self._aligned_cuts(bounds, S, nparts)
                if cuts:
                    edges = [0] + cuts + [S]
                    return [(idle[i], edges[i], edges[i + 1] - edges[i])
                            for i in range(len(edges) - 1)], False
                # single tagged item: fall through to whole-batch
            else:
                base, rem = divmod(S, nparts)
                plan, r0 = [], 0
                for i in range(nparts):
                    rn = base + (1 if i < rem else 0)
                    plan.append((idle[i], r0, rn))
                    r0 += rn
                return plan, False
        pick = cands[0]
        if self.cost_aware and nbytes and len(cands) > 1:
            p_least = pick.predict(nbytes)
            if p_least is not None:
                self._c["cost_placements"] += 1
                best, p_best = pick, p_least
                for lane in cands[1:]:
                    p = lane.predict(nbytes)
                    if p is not None and p < p_best:
                        best, p_best = lane, p
                if best is not pick and p_best * COST_MARGIN < p_least:
                    pick = best
                    self._c["cost_diverged"] += 1
        return [(pick, 0, S)], False

    @staticmethod
    def _aligned_cuts(bounds: list, S: int, nparts: int) -> list:
        """Up to nparts-1 strictly-increasing cut points drawn from
        the item boundaries, each nearest the even-split ideal for the
        rows left — parts stay balanced to the extent item sizes
        allow."""
        cuts: list = []
        last = 0
        remaining = nparts
        avail = [b for b in bounds if 0 < b < S]
        while remaining > 1 and avail:
            want = last + max(1, round((S - last) / remaining))
            best = min(avail, key=lambda b: abs(b - want))
            cuts.append(best)
            last = best
            avail = [b for b in avail if b > best]
            remaining -= 1
        return cuts

    # -- mesh dispatch (pod scale: one batch across the device mesh) -------

    def _mesh_eligible(self, chan: PipelineChannel, items: list,
                       nbytes: int) -> bool:
        """Mesh mode is chosen when the channel can shard_map, the
        coalesced batch exceeds a single lane's staging budget, and no
        item carries the degrade latch (a batch that already fell off
        the mesh must finish on row splits, bit-identically)."""
        return (chan.mesh_fn is not None and self.mesh_min_bytes > 0
                and nbytes >= self.mesh_min_bytes
                and not any(it.no_mesh for it in items))

    @staticmethod
    def _parse_mesh_spec(spec: str, avail: int) -> tuple | None:
        """osd_ec_device_mesh -> (n_dp, n_ls): "auto" spans every
        active lane on the chunk-length axis, an integer caps the
        member count, "AxB" lays out dp x ls explicitly (None when
        the layout cannot be satisfied by `avail` lanes)."""
        s = str(spec or "auto").strip().lower()
        if "x" in s:
            try:
                a, b = s.split("x", 1)
                n_dp, n_ls = max(1, int(a)), max(1, int(b))
            except ValueError:
                return None
            if n_dp * n_ls > avail:
                return None
            return n_dp, n_ls
        if s.isdigit():
            n = min(int(s), avail)
            return (1, n) if n >= 2 else None
        return 1, avail

    def _mesh_plane(self) -> _MeshPlane | None:
        """The current mesh plane, built lazily from the active lanes.
        Injected per-device faults are rolled on every member here, at
        mesh placement — a hit quarantines that lane, drops the plane
        and degrades THIS dispatch to surviving-lane row splits (the
        ladder's next rung)."""
        now = time.monotonic()
        fs = faults.get()
        with self._lock:
            plane = self._mesh
            if plane is None:
                ds = self._devset
                if ds is None:
                    return None
                lanes = [l for l in ds.lanes
                         if not l.quarantined and not l.stuck(now)
                         and l.device is not None]
                if len(lanes) < 2:
                    return None
                parsed = self._parse_mesh_spec(self.device_mesh,
                                               len(lanes))
                if parsed is None:
                    return None
                n_dp, n_ls = parsed
                if n_dp * n_ls < 2:
                    return None
                plane = _MeshPlane(lanes[: n_dp * n_ls], n_dp, n_ls)
                self._mesh = plane
            for lane in plane.lanes:
                if lane.quarantined or fs.tpu_error(device=lane.index):
                    if not lane.quarantined:
                        self._quarantine_locked(
                            lane, "injected device error")
                        self._c["device_errors"] += 1
                        lane.errors += 1
                    self._c["mesh_degrades"] += 1
                    self._mesh = None
                    return None
        return plane

    def _dispatch_mesh(self, chan: PipelineChannel, items: list,
                       batch: np.ndarray) -> bool:
        """Serve one coalesced mega-batch sharded across the mesh.
        Returns True when the batch was handled (served, or requeued
        by the degrade ladder); False to fall through to row-split
        placement (no plane, mesh kernel still compiling, or a member
        fault rolled at placement).

        Runs inline on the dispatcher thread like the host path: a
        pod-scale dispatch IS the backpressure that coalesces the
        queue behind it."""
        plane = self._mesh_plane()
        if plane is None:
            return False
        donate = (len(items) == 1 and items[0].arena is not None
                  and items[0].cache is None)
        keep = hbm_cache.get().capacity > 0 and \
            any(it.cache is not None for it in items)
        t0 = time.perf_counter()
        t_m0 = time.monotonic()
        try:
            res = chan.mesh_fn(batch, plane, donate=donate,
                               keep_resident=keep)
        except Exception as e:
            self._mesh_failed(chan, items, e)
            return True
        if res is None:
            return False
        outs, resident = res
        secs = max(time.perf_counter() - t0, 1e-9)
        t_m1 = time.monotonic()
        for it in items:
            # the mesh serve stages+computes+fetches inline: one
            # device window (H2D/compute/D2H not separable here)
            it.ph["issue"] = t_m0
            it.ph["collect0"] = t_m1
            it.ph["done"] = t_m1
        outs = tuple(np.asarray(o) for o in outs)
        d2h = sum(int(o.nbytes) for o in outs)
        with self._lock:
            self._c["dispatches"] += 1
            self._c["dev_dispatches"] += 1
            self._c["mesh_dispatches"] += 1
            self._c["bytes_h2d"] += batch.nbytes
            self._c["bytes_d2h"] += d2h
            if donate:
                self._c["arena_donations"] += 1
            if len(items) == 1 and items[0].arena is not None:
                # the arena's upload WAS the staging copy — donated
                # (device buffer consumed by the computation) or kept
                # resident for the HBM cache, either way no further
                # host materialization happened: ec.stage retires for
                # this write (resolve skips the note)
                items[0].arena.consumed = True
        try:
            chan.record("dev", batch.nbytes, secs, len(plane.lanes),
                        device=None)
        except Exception:
            pass
        if resident is not None:
            self._stage_mesh_cache(items, plane, outs, resident)
        self._resolve(items, "dev", outs)
        return True

    def _mesh_failed(self, chan: PipelineChannel, items: list,
                     e: Exception) -> None:
        """A mesh computation failed mid-flight.  The error is not
        attributable to one chip, so no lane quarantines on this rung:
        the plane drops and the batch requeues latched off the mesh —
        surviving-lane row splits serve it bit-identically, and a
        genuinely bad chip then fails its row-split part and
        quarantines through the existing single-lane ladder."""
        with self._lock:
            self._c["device_errors"] += 1
            self._c["mesh_degrades"] += 1
            self._mesh = None
            for it in items:
                it.no_mesh = True
            self._requeue_locked(chan, items)
        from ..utils.dout import DoutLogger
        DoutLogger("ops", "ec-pipeline").warn(
            "EC mesh dispatch failed (%s: %s): degrading batch to "
            "row-split placement", type(e).__name__, e)

    def _stage_mesh_cache(self, items: list, plane: _MeshPlane,
                          outs: tuple, resident: tuple) -> None:
        """Mesh-resident HBM cache staging: entries address the WHOLE
        mesh (their stripes are sharded device arrays), pinned to
        every member lane — a quarantine of any one drops them."""
        dev_data, dev_parity, pad = resident
        off = 0
        for it in items:
            if it.cache is not None:
                try:
                    hbm_cache.get().stage(
                        it.cache, plane.lane_indices,
                        dev_data[off: off + it.n],
                        dev_parity[off: off + it.n],
                        outs[1][off: off + it.n], pad=pad)
                except Exception:
                    pass    # cache is an optimization, never a fault
            off += it.n

    def _to_device(self, padded: np.ndarray, lane: _Lane):
        """Stage one part's H2D upload onto `lane`'s chip (runs on the
        lane's stager thread — uploads to different chips proceed in
        parallel and overlap the previous batch's compute).  Every
        byte that actually crosses the boundary is accounted."""
        if lane.device is None:
            return padded
        try:
            import jax
            dev = jax.device_put(padded, lane.device)
        except Exception:
            return padded
        with self._lock:
            lane.bytes_h2d += padded.nbytes
            self._c["bytes_h2d"] += padded.nbytes
        return dev

    def _requeue_locked(self, chan: PipelineChannel, items: list) -> None:
        """Push redrained items back to the FRONT of their channel
        queue (they were submitted first; FIFO fairness holds).  A
        dispatch never mixes tenants, so one requeue batch shares one
        (channel, tag) queue."""
        self._chans[chan.key] = chan
        tag = items[0].tag if items else None
        q = self._queues.setdefault((chan.key, tag), deque())
        for it in items:
            # quarantine/failure degrade marker for the op trace
            it.ph["requeues"] = it.ph.get("requeues", 0) + 1
        q.extendleft(reversed(items))
        self._c["redrained"] += len(items)
        self._work_cv.notify()

    # -- dispatch ----------------------------------------------------------

    def _dispatch(self, chan: PipelineChannel, items: list) -> None:
        batch = _cat_items(items)
        nbytes = batch.nbytes
        use_dev = False
        if chan.device_fn is not None and not self._stalled:
            try:
                use_dev = bool(chan.route(nbytes))
            except Exception:
                use_dev = False
        if use_dev:
            self._ensure_devset()
            if self._mesh_eligible(chan, items, nbytes) and \
                    self._dispatch_mesh(chan, items, batch):
                return
            bounds = None
            if hbm_cache.get().capacity > 0 and \
                    any(it.cache is not None for it in items):
                bounds, r = [], 0
                for it in items[:-1]:
                    r += it.n
                    bounds.append(r)
            with self._lock:
                plan, exhausted = self._plan_locked(batch.shape[0],
                                                    nbytes, bounds)
            if exhausted:
                # every chip quarantined: the channel owner degrades
                # (tpu plugin -> host matrix codec) and this batch —
                # plus everything behind it — drains to the host fn
                with self._lock:
                    self._c["drained_to_host"] += len(items)
                chan.on_error(RuntimeError(
                    "all EC device lanes quarantined"))
            elif plan:
                parts_items = None
                if len(plan) > 1 and bounds is not None:
                    # item-aligned split: each part is an INDEPENDENT
                    # dispatch carrying its own items (no group), so
                    # every part resolves — and stages its tagged
                    # items into the HBM cache — on its own lane
                    parts_items, it_iter = [], iter(items)
                    for _lane, _r0, rn in plan:
                        sub, acc = [], 0
                        while acc < rn:
                            nxt = next(it_iter)
                            sub.append(nxt)
                            acc += nxt.n
                        parts_items.append(sub)
                self._issue(chan, items, batch, plan, parts_items)
                return          # staged onto its lanes (the stagers
                                # upload + dispatch, or host-serve a
                                # cold fn / redrain a dead lane)
            # no lane free right now: host serves
        self._run_host(chan, items, batch)

    def _issue(self, chan: PipelineChannel, items: list,
               batch: np.ndarray, plan: list,
               parts_items: list | None = None) -> bool:
        """Hand the placed (possibly split) batch to its lanes'
        stagers.  The dispatcher never touches the device: uploads and
        async compute issue on the per-lane stager threads, so it is
        free to keep coalescing while parts stream H2D in parallel.
        Returns True when the batch is claimed (staged, or redrained
        after hitting a dead lane); False never — host fallback for a
        cold (not-warm) device fn happens on the stager.

        `parts_items` (item-aligned splits) makes each part its own
        groupless dispatch over exactly its items."""
        group = None
        if len(plan) > 1:
            if parts_items is None:
                group = _Group(chan, items, len(plan), batch.nbytes,
                               time.perf_counter())
            with self._lock:
                self._c["split_dispatches"] += 1
        for gidx, (lane, r0, rn) in enumerate(plan):
            part = batch[r0: r0 + rn] if len(plan) > 1 else batch
            p_items = (items if group is None else []) \
                if parts_items is None else parts_items[gidx]
            staged = _Staged(chan, p_items, part, rn, group, gidx)
            with self._lock:
                if not lane.alive or lane.quarantined:
                    # placement raced a devset rebuild or quarantine:
                    # requeue for a healthy lane (or the host path).
                    # Row-split: the whole batch, parts already staged
                    # discard via the failed group.  Item-aligned:
                    # earlier parts are independent dispatches that
                    # resolve on their lanes — requeue only the parts
                    # not yet staged.
                    if parts_items is not None:
                        self._requeue_locked(
                            chan, [it for sub in parts_items[gidx:]
                                   for it in sub])
                        return True
                    already = group is not None and group.failed
                    if group is not None:
                        group.failed = True
                    if not already:
                        self._requeue_locked(chan, items)
                    return True
                lane.stage_q.append(staged)
                self._inflight_cv.notify_all()
        return True

    # -- stagers (one thread per lane: the H2D half of the plane) ----------

    def _stage_loop(self, lane: _Lane) -> None:
        while True:
            with self._lock:
                while self._running and lane.alive and \
                        not lane.stage_q:
                    self._inflight_cv.wait()
                if not self._running or not lane.alive:
                    # a retired lane (reset_devices) must not strand
                    # queued parts — their futures would never
                    # resolve and the op threads waiting on them
                    # would wedge: requeue for the fresh device set
                    while lane.stage_q:
                        staged = lane.stage_q.popleft()
                        already = staged.group is not None and \
                            staged.group.failed
                        if staged.group is not None:
                            staged.group.failed = True
                        if not already:
                            self._requeue_locked(
                                staged.chan,
                                staged.items if staged.group is None
                                else staged.group.items)
                    return
                staged = lane.stage_q.popleft()
                if lane.quarantined:
                    # quarantined after staging: redrain to survivors
                    already = staged.group is not None and \
                        staged.group.failed
                    if staged.group is not None:
                        staged.group.failed = True
                    if not already:
                        self._requeue_locked(
                            staged.chan,
                            staged.items if staged.group is None
                            else staged.group.items)
                    continue
                lane.staging += 1
                lane.stage_started = time.monotonic()
                self._busy += 1
            try:
                self._stage_one(staged, lane)
            except Exception as e:
                for it in (staged.items if staged.group is None
                           else staged.group.items):
                    if not it.fut.done():
                        it.fut.set_exception(e)
            finally:
                with self._lock:
                    lane.staging -= 1
                    lane.stage_started = None
                    self._busy -= 1
                    self._fetch_cv.notify_all()

    def _stage_one(self, staged: _Staged, lane: _Lane) -> None:
        """Upload one part and issue its async device dispatch."""
        chan = staged.chan
        its = staged.items if staged.group is None \
            else staged.group.items
        t_s0 = time.monotonic()
        padded = pad_batch(staged.part)
        dev_arr = self._to_device(padded, lane)
        t_s1 = time.monotonic()
        for it in its:
            # split-group parts stage concurrently; the per-item
            # stamps keep the widest window (min start, max end)
            it.ph["stage0"] = min(it.ph.get("stage0", t_s0), t_s0)
            it.ph["stage1"] = max(it.ph.get("stage1", t_s1), t_s1)
            it.ph["issue"] = it.ph["stage1"]
        t0 = time.perf_counter()
        try:
            out = chan.device_fn(dev_arr, lane.device)
        except Exception as e:
            self._device_failed_dispatch(chan, lane, staged.group,
                                         staged, e)
            return
        if out is None:
            # not warm on this device yet (background compile kicked
            # off): host serves the whole batch.  For a split group
            # only the FIRST cold part host-serves (every item lives
            # at group level); other parts' outputs discard.
            if staged.group is not None:
                with self._lock:
                    serve = not staged.group.failed
                    staged.group.failed = True
                if serve:
                    items = staged.group.items
                    self._run_host(chan, items, _cat_items(items))
            else:
                self._run_host(chan, staged.items, staged.part)
            return
        disp = _Dispatch(chan, staged.items, staged.S, out, t0,
                         staged.part.nbytes, lane, staged.group,
                         staged.gidx, dev_in=dev_arr)
        with self._lock:
            if not lane.alive:
                # reset_devices retired this lane mid-upload — its
                # collector may already be gone, so an append here
                # would never be collected: requeue for the fresh
                # device set instead
                already = staged.group is not None and \
                    staged.group.failed
                if staged.group is not None:
                    staged.group.failed = True
                if not already:
                    self._requeue_locked(
                        chan, staged.items if staged.group is None
                        else staged.group.items)
                return
            lane.inflight.append(disp)
            self._inflight_cv.notify_all()

    def _device_failed_dispatch(self, chan, lane, group, staged,
                                e: Exception) -> None:
        """A device_fn blew up at issue time: quarantine the lane and
        redrain onto survivors (host only when none remain).  Split
        parts fail concurrently on different stagers — the group's
        failed latch guarantees the items requeue exactly once."""
        items = staged.items if group is None else group.items
        with self._lock:
            self._c["device_errors"] += 1
            lane.errors += 1
            self._quarantine_locked(lane, f"{type(e).__name__}: {e}")
            already_requeued = False
            if group is not None:
                already_requeued = group.failed
                group.failed = True
            ds = self._devset
            # devset mid-rebuild counts as having survivors: requeue
            # and let the fresh lanes (or the host path) serve it
            active_left = len(ds.active()) if ds is not None else 1
        self._log_quarantine(lane, active_left)
        if already_requeued:
            return
        if active_left:
            with self._lock:
                self._requeue_locked(chan, items)
            return
        with self._lock:
            self._c["drained_to_host"] += len(items)
        chan.on_error(e)
        self._run_host(chan, items, _cat_items(items))

    # -- collectors (one thread per lane) ----------------------------------

    def _collect_loop(self, lane: _Lane) -> None:
        while True:
            with self._lock:
                while self._running and lane.alive and \
                        not lane.inflight:
                    self._inflight_cv.wait()
                if not self._running:
                    return
                if not lane.inflight:
                    return              # devset rebuilt, lane drained
                disp = lane.inflight.popleft()
                lane.collect_started = time.monotonic()
                self._busy += 1
            try:
                self._collect_one(disp)
            except Exception as e:
                # never kill the loop: a dead collector would leak
                # _busy and wedge every producer blocked in result()
                for it in (disp.items if disp.group is None
                           else disp.group.items):
                    if not it.fut.done():
                        it.fut.set_exception(e)
            finally:
                with self._lock:
                    lane.collect_started = None
                    self._busy -= 1
                    self._fetch_cv.notify_all()

    def _collect_one(self, disp: _Dispatch) -> None:
        lane = disp.lane
        try:
            # parity-only readback: exactly the channel fn's outputs
            # cross D2H (an encode fetches (S_pad, m, L) parity + the
            # 4*(k+m)-byte CRC vector per stripe — never the data
            # shards the host already holds)
            t_c0 = time.monotonic()
            outs = tuple(np.asarray(o) for o in disp.out)
            t_c1 = time.monotonic()
            for it in (disp.items if disp.group is None
                       else disp.group.items):
                it.ph["collect0"] = min(it.ph.get("collect0", t_c0),
                                        t_c0)
                it.ph["done"] = max(it.ph.get("done", t_c1), t_c1)
            d2h = sum(int(o.nbytes) for o in outs)
            now = time.perf_counter()
            # marginal service time PER LANE: overlap with this chip's
            # previous fetch does not double-bill — this is the
            # amortized per-chip sec/byte the measured router scores
            start = max(disp.t0, lane.last_fetch_done)
            lane.last_fetch_done = now
            secs = max(now - start, 1e-9)
            with self._lock:
                depth = len(lane.inflight) + 1
                self._c["dispatches"] += 1
                self._c["dev_dispatches"] += 1
                self._c["bytes_d2h"] += d2h
                lane.dispatches += 1
                lane.stripes += disp.S
                lane.nbytes += disp.nbytes
                lane.bytes_d2h += d2h
                lane.note_service(disp.nbytes, secs)
            try:
                disp.chan.record("dev", disp.nbytes, secs, depth,
                                 device=lane.index)
            except Exception:
                pass
            if disp.group is None:
                self._stage_cache(disp, outs)
            outs = tuple(o[: disp.S] for o in outs)
            if disp.group is None:
                self._resolve(disp.items, "dev", outs)
            else:
                self._group_part_done(disp, outs)
        except Exception as e:
            self._device_failed_fetch(disp, e)

    def _stage_cache(self, disp: _Dispatch, outs: tuple) -> None:
        """Keep cache-tagged items' stripes in HBM: device SLICES of
        the already-uploaded input and the already-computed parity —
        zero extra transfer.  Only row-split group parts skip (an
        item's rows straddle part boundaries there) — placement cuts
        cache-tagged batches at item boundaries precisely so their
        parts arrive here as independent dispatches."""
        if disp.dev_in is None or len(disp.out) < 2 or \
                not any(it.cache is not None for it in disp.items):
            return
        off = 0
        for it in disp.items:
            if it.cache is not None:
                try:
                    hbm_cache.get().stage(
                        it.cache, disp.lane.index,
                        disp.dev_in[off: off + it.n],
                        disp.out[0][off: off + it.n],
                        outs[1][off: off + it.n])
                except Exception:
                    pass        # cache is an optimization, never a fault
            off += it.n

    def _group_part_done(self, disp: _Dispatch, outs: tuple) -> None:
        g = disp.group
        with self._lock:
            if g.failed:
                return                 # another part quarantined; the
            g.outs[disp.gidx] = outs   # items were already requeued
            g.pending -= 1
            done = g.pending == 0
        if done:
            # group-level routing sample at the FULL mega-batch size:
            # the per-part records capture per-chip marginal cost in
            # their (smaller) buckets; this one keeps the bucket the
            # host path records at comparable, scoring the fleet's
            # issue-to-complete cost for a batch this big
            try:
                g.chan.record(
                    "dev", g.nbytes,
                    max(time.perf_counter() - g.t0, 1e-9), g.nparts)
            except Exception:
                pass
            width = len(g.outs[0])
            cat = tuple(
                np.concatenate([g.outs[i][j] for i in range(g.nparts)])
                for j in range(width))
            self._resolve(g.items, "dev", cat)

    def _device_failed_fetch(self, disp: _Dispatch, e: Exception) -> None:
        """Async-dispatch errors surface at fetch: quarantine the lane
        and redrain the WHOLE batch onto surviving chips (or, with no
        chips left, degrade the channel owner and re-run on host) —
        nothing queued is lost, results stay bit-identical."""
        lane = disp.lane
        chan = disp.chan
        items = disp.items if disp.group is None else disp.group.items
        with self._lock:
            self._c["device_errors"] += 1
            lane.errors += 1
            self._quarantine_locked(lane, f"{type(e).__name__}: {e}")
            already_requeued = False
            if disp.group is not None:
                already_requeued = disp.group.failed
                disp.group.failed = True
            ds = self._devset
            active_left = len(ds.active()) if ds is not None else 1
        self._log_quarantine(lane, active_left)
        if already_requeued:
            return
        if active_left:
            with self._lock:
                self._requeue_locked(chan, items)
            return
        with self._lock:
            self._c["drained_to_host"] += len(items)
        chan.on_error(e)
        self._run_host(chan, items, _cat_items(items))

    # -- shared ------------------------------------------------------------

    def _run_host(self, chan: PipelineChannel, items: list,
                  batch: np.ndarray) -> None:
        t0 = time.perf_counter()
        t_h0 = time.monotonic()
        try:
            outs = tuple(np.asarray(o) for o in chan.host_fn(batch))
        except Exception as e:
            for it in items:
                if not it.fut.done():
                    it.fut.set_exception(e)
            return
        t_h1 = time.monotonic()
        for it in items:
            it.ph["host0"] = t_h0
            it.ph["host1"] = t_h1
        with self._lock:
            self._c["dispatches"] += 1
            self._c["host_dispatches"] += 1
        try:
            chan.record("host", batch.nbytes,
                        max(time.perf_counter() - t0, 1e-9), 1)
        except Exception:
            pass
        self._resolve(items, "host", outs)

    @staticmethod
    def _resolve(items: list, path: str, outs: tuple) -> None:
        off = 0
        for it in items:
            ar = it.arena
            if ar is not None and not ar.consumed and not ar.noted:
                # the staged arena was NOT subsumed by a donated mesh
                # upload (host or row-split serve): its staging copy
                # is a real host materialization after all — account
                # it exactly where the plain-buffer path would have
                ar.noted = True
                copyaudit.note("ec.stage", ar.payload_bytes)
            sl = tuple(o[off: off + it.n] for o in outs)
            off += it.n
            if not it.fut.done():
                # phase stamps ride the future itself: the producer's
                # op thread turns them into TrackedOp spans without
                # any pipeline->tracker coupling
                it.fut.trace_phases = dict(it.ph)
                it.fut.set_result((path, sl))


# ---------------------------------------------------------------------------
# Process-wide singleton (all producers in a process share one queue —
# that IS the cross-op coalescing) + plugin-agnostic channels.
# ---------------------------------------------------------------------------

_global: EcDevicePipeline | None = None
_glock = threading.Lock()


def get() -> EcDevicePipeline:
    global _global
    if _global is None:
        with _glock:
            if _global is None:
                _global = EcDevicePipeline()
    return _global


def configure(depth: int | None = None,
              coalesce_wait: float | None = None,
              max_batch: int | None = None,
              device_shards=_UNSET,
              scrub_weight: float | None = None,
              split_min: int | None = None,
              cost_aware: bool | None = None,
              hbm_cache_bytes: int | None = None,
              mesh_min_bytes: int | None = None,
              device_mesh: str | None = None,
              qos_cost_unit: int | None = None) -> EcDevicePipeline:
    """Tune the shared pipeline (daemon startup applies its conf)."""
    p = get()
    if depth is not None:
        p.depth = max(1, int(depth))
    if coalesce_wait is not None:
        p.coalesce_wait = max(0.0, float(coalesce_wait))
    if max_batch is not None:
        p.max_batch = max(1, int(max_batch))
    if scrub_weight is not None:
        p.scrub_weight = max(0.01, float(scrub_weight))
    if split_min is not None:
        p.split_min = max(1, int(split_min))
    if cost_aware is not None:
        p.cost_aware = bool(cost_aware)
    if hbm_cache_bytes is not None:
        hbm_cache.configure(hbm_cache_bytes)
    if mesh_min_bytes is not None:
        p.mesh_min_bytes = int(mesh_min_bytes)
    if device_mesh is not None and device_mesh != p.device_mesh:
        p.device_mesh = str(device_mesh)
        with p._lock:
            p._mesh = None      # axis layout change rebuilds the plane
    if qos_cost_unit is not None:
        p.qos_cost_unit = max(0, int(qos_cost_unit))
    if device_shards is not _UNSET and \
            device_shards != p.device_shards:
        # shard-count change rebuilds the device set (and clears any
        # quarantine latches with it)
        if p._devset is not None:
            p.reset_devices(device_shards)
        else:
            p.device_shards = device_shards
    return p


def stats() -> dict:
    return get().stats()


def configure_qos(specs: dict, cost_unit: int | None = None) -> None:
    """Install per-pool dmClock service classes ({pool: QosSpec}) on
    the dispatch-lane picker.  Called by every daemon's
    _qos_reconfigure — the pipeline is process-wide, so in-process
    daemons (one shared conf) converge on the same class set.  Rates
    apply at DISPATCH-pick granularity, BYTES-WEIGHTED: each pick is
    charged 1 + head_batch_bytes/cost_unit (osd_qos_cost_bytes_unit),
    so reservation/weight/limit meter a tenant's bytes through the
    lanes, not its dispatch count; the op queue's per-op rates remain
    the precise enforcement point."""
    p = get()
    if cost_unit is not None:
        p.qos_cost_unit = max(0, int(cost_unit))
    with p._lock:
        p._qos.configure(dict(specs))
        p._qos_enabled = bool(specs)


def qos_stats() -> dict:
    """The dispatch-lane half of the perf-dump `qos` block."""
    return get()._qos.stats()


# -- deep-scrub CRC channels -------------------------------------------------
#
# Keyed per shard size; device fn is the jitted CRC fold, warmed on a
# background thread PER DEVICE exactly like TpuBackend's codec fns so
# the shared dispatcher never blocks tens of seconds inside a
# first-shape compile.

_crc_channels: dict[int, PipelineChannel] = {}
# warmed jitted fns are pinned HERE, not re-fetched through
# ec_kernels' lru_cache: an LRU eviction would otherwise recompile
# inline on the shared dispatcher thread while the readiness set
# still claims the shape is warm (TpuBackend couples _fns/_ready the
# same way)
_crc_fns: dict = {}
_crc_ready: set = set()
_crc_warming: set = set()
_crc_warm_failed: set = set()
_crc_lock = threading.Lock()
# sticky device-dead latch (the tpu plugin's degrade equivalent): a
# REAL post-warm device failure that exhausts every lane must not
# cost a failing dispatch + host re-run on every later scrub batch
# until daemon restart
_crc_device_dead = False


def _crc_on_error(e: Exception) -> None:
    global _crc_device_dead
    if not _crc_device_dead:
        _crc_device_dead = True
        from ..utils.dout import DoutLogger
        DoutLogger("ops", "ec-pipeline").warn(
            "scrub CRC device path failed (%s: %s): latching to host "
            "fold", type(e).__name__, e)


def _device_warm_key(device):
    if device is None:
        return None
    return (getattr(device, "platform", "?"), getattr(device, "id", 0))


def _crc_device_fn(size: int):
    def device_fn(padded, device=None):
        key = (size, tuple(padded.shape), _device_warm_key(device))
        with _crc_lock:
            fn = _crc_fns.get(key)
            if fn is None:
                # negative-cache warm failures (TpuBackend does the
                # same): re-warming every dispatch would churn a
                # thread + a failing ~10s backend init per batch
                if key not in _crc_warming and \
                        key not in _crc_warm_failed:
                    _crc_warming.add(key)
                    threading.Thread(
                        target=_warm_crc,
                        args=(size, tuple(padded.shape), device),
                        daemon=True, name="ec-crc-warm").start()
                return None
        return (fn(padded),)

    return device_fn


# mesh-sharded scrub folds: one mega CRC batch shard_maps its chunk
# axis across the mesh plane, per-shard partials combine on device
# (ec_kernels.make_mesh_crc_fn).  Warm registry mirrors _crc_fns:
# compiles happen off the dispatcher, a cold key row-splits instead.
_crc_mesh_fns: dict = {}
_crc_mesh_warming: set = set()
_crc_mesh_failed: set = set()


def _crc_mesh_fn(size: int):
    def mesh_fn(batch, plane, donate=False, keep_resident=False):
        if _crc_device_dead:
            return None
        key = (size, batch.shape[0], plane.key())
        with _crc_lock:
            fn = _crc_mesh_fns.get(key)
            if fn is None:
                if key not in _crc_mesh_warming and \
                        key not in _crc_mesh_failed:
                    _crc_mesh_warming.add(key)
                    threading.Thread(
                        target=_warm_crc_mesh,
                        args=(size, batch.shape[0], plane.key()),
                        daemon=True, name="ec-crc-mesh-warm").start()
                return None
        return (fn(batch),), None

    return mesh_fn


def _warm_crc_mesh(size: int, B: int, plane_key: tuple) -> None:
    from . import ec_kernels
    key = (size, B, plane_key)
    fn = None
    try:
        devices, n_dp, n_ls = plane_key
        fn = ec_kernels.make_mesh_crc_fn(size, devices, n_dp, n_ls)
        fn(np.zeros((B, size), dtype=np.uint8))
    except Exception:
        fn = None       # negative-cached below; row-split/host serves
    finally:
        with _crc_lock:
            _crc_mesh_warming.discard(key)
            if fn is not None:
                if len(_crc_mesh_fns) > 64:
                    _crc_mesh_fns.clear()
                _crc_mesh_fns[key] = fn
            else:
                _crc_mesh_failed.add(key)


def _warm_crc(size: int, shape: tuple, device=None) -> None:
    from . import ec_kernels
    key = (size, shape, _device_warm_key(device))
    fn = None
    try:
        fn = ec_kernels.make_crc_fn(size)
        probe = np.zeros(shape, dtype=np.uint8)
        if device is not None:
            import jax
            probe = jax.device_put(probe, device)
        np.asarray(fn(probe))
    except Exception:
        fn = None   # negative-cached below; host path keeps serving
    finally:
        with _crc_lock:
            _crc_warming.discard(key)
            if fn is not None:
                if len(_crc_fns) > 256:
                    _crc_fns.clear()
                    _crc_ready.clear()
                _crc_fns[key] = fn
                _crc_ready.add(key)
            else:
                _crc_warm_failed.add(key)


def crc_channel(size: int,
                max_coalesce: int | None = None) -> PipelineChannel:
    """Shared channel computing CRC32C(seed 0) per row of (B, size)
    batches; future outputs are ((B,) uint32,).  `max_coalesce`
    bounds stripes per dispatch (the scrubber passes its
    osd_deep_scrub_stripe_batch so coalescing cannot exceed the
    operator's per-dispatch device-memory cap).  Scrub-class QoS:
    these channels yield dispatch slots to client-write encodes under
    contention (osd_ec_pipeline_scrub_weight)."""
    with _crc_lock:
        chan = _crc_channels.get(size)
        if chan is None:
            from . import crc32c as crc_mod
            from ..utils import faults as faults_mod

            def host_fn(batch):
                return (crc_mod.crc32c_batch(batch),)

            def route(nbytes):
                return not _crc_device_dead and \
                    not faults_mod.get().tpu_error()

            chan = PipelineChannel(
                key=("crc", size), host_fn=host_fn,
                device_fn=_crc_device_fn(size), route=route,
                on_error=_crc_on_error, max_coalesce=max_coalesce,
                qos_class="scrub", mesh_fn=_crc_mesh_fn(size))
            _crc_channels[size] = chan
        elif max_coalesce is not None:
            # several daemons share this in-process registry: honor
            # the STRICTEST per-dispatch cap any of them configured
            chan.max_coalesce = max_coalesce if chan.max_coalesce \
                is None else min(chan.max_coalesce, max_coalesce)
        return chan
