"""Pallas TPU kernels: GF(2^8) erasure encode on packed bytes.

The XLA formulation in ec_kernels.py materializes an 8x int8 bit-plane
expansion of every chunk in HBM (unpack -> matmul -> pack are separate
fusions), so the pass is HBM-bound at ~1/6 of the packed-byte ceiling.
These kernels keep the expansion in VMEM: each grid cell DMAs a packed
uint8 tile, unpacks to bit-planes in registers/VMEM, runs the GF(2)
matmul on the MXU, folds mod 2, and repacks — HBM traffic is exactly
input + parity bytes.

Replaces the role of the reference's ISA-L assembly
(/root/reference/src/erasure-code/isa/isa-l/erasure_code/*.asm.s,
gf_{2..6}vect_dot_prod pshufb kernels) on TPU.

The generator matrix enters as an (8m, k, 8) int8 constant: entry
[r, j, b] is bit r of the GF(2^8) column multiplier for input byte j's
bit b (expand_bitmatrix column j*8+b).  The contraction folds (k, 8)
against the tile's (k, 8, TL) bit-planes in one dot_general, so no
bit-plane reshape/relayout ever happens.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import gf

# lanes per grid cell; 8 bit-planes of a TL-byte tile = TL*8k int8 in
# VMEM (k=8, TL=16384 -> 8 MB peak intermediates), inside ~16 MB VMEM.
# Measured on v5e: 16384 beats 4096/8192 (fewer cells amortize per-cell
# DMA setup) while 32768 regresses (VMEM pressure kills double
# buffering).
DEFAULT_TILE = 16384


def _g3_from_matrix(matrix: np.ndarray) -> np.ndarray:
    """(m, k) GF(2^8) matrix -> (8m, 8k) 0/1 int8, rows bit-major.

    Row b*m + i carries output bit b of parity byte i, so the kernel
    repacks with 8 contiguous static slices instead of a reshape or a
    second (unsupported int-mixing) matmul.
    """
    m, k = matrix.shape
    bits = gf.expand_bitmatrix(np.asarray(matrix, dtype=np.uint8), 8)
    perm = [8 * i + b for b in range(8) for i in range(m)]
    return bits[perm].astype(np.int8)


def _encode_kernel(g_ref, mask_ref, x_ref, out_ref, *, m: int, k: int):
    x = x_ref[0]                                   # (k, TL) uint8
    # flat (8k, TL) bit-planes without reshapes: row r = byte r//8's
    # bit r%8 (expand_bitmatrix column order).  The test stays in the
    # uint8 domain (4x the VPU lane density of int32 shifts): row r's
    # mask is the constant 1 << (r % 8), broadcast from the mask input.
    xrep = jnp.repeat(x, 8, axis=0)                # (8k, TL)
    bits = ((xrep & mask_ref[:]) != 0).astype(jnp.int8)
    acc = jax.lax.dot_general(
        g_ref[:], bits,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )                                              # (8m, TL) bit-major rows
    parity = acc[0:m] & 1
    for b in range(1, 8):
        parity |= (acc[b * m:(b + 1) * m] & 1) << b
    out_ref[0] = parity.astype(jnp.uint8)


@functools.lru_cache(maxsize=256)
def _encode_call(g_key: bytes, mk: tuple[int, int], L: int, tile: int,
                 interpret: bool):
    m, k = mk
    g3 = np.frombuffer(g_key, dtype=np.int8).reshape(8 * m, 8 * k)
    g_const = jnp.asarray(g3)
    ntiles = L // tile

    kernel = functools.partial(_encode_kernel, m=m, k=k)
    mask_np = np.tile((1 << (np.arange(8 * k) % 8)).astype(np.uint8)
                      [:, None], (1, tile))
    mask_const = jnp.asarray(mask_np)

    @jax.jit
    def run(data):                                  # (B, k, L) uint8
        B = data.shape[0]
        return pl.pallas_call(
            kernel,
            grid=(B, ntiles),
            in_specs=[
                pl.BlockSpec((8 * m, 8 * k), lambda b, j: (0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((8 * k, tile), lambda b, j: (0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, k, tile), lambda b, j: (b, 0, j),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((1, m, tile), lambda b, j: (b, 0, j),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((B, m, L), jnp.uint8),
            interpret=interpret,
        )(g_const, mask_const, data)

    return run


def _pick_tile(L: int, tile: int = DEFAULT_TILE) -> int | None:
    """Largest lane tile (multiple of 128) dividing L, or None."""
    t = min(tile, L)
    while t >= 128:
        if L % t == 0 and t % 128 == 0:
            return t
        t -= 128
    return None


def supports(L: int) -> bool:
    return _pick_tile(L) is not None


def make_encode_fn(matrix: np.ndarray, L: int, tile: int = DEFAULT_TILE,
                   interpret: bool | None = None):
    """Jitted pallas encode: (B, k, L) uint8 -> (B, m, L) uint8 parity.

    L must be a multiple of 128 (use ec_kernels.make_codec_fn for odd
    sizes).  `interpret` defaults to True off-TPU so tests exercise the
    same kernel on the CPU mesh.
    """
    m, k = np.asarray(matrix).shape
    t = _pick_tile(L, tile)
    if t is None:
        raise ValueError(f"L={L} not tileable (needs multiple of 128)")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    g3 = _g3_from_matrix(np.asarray(matrix, dtype=np.uint8))
    fn = _encode_call(g3.tobytes(), (m, k), L, t, interpret)

    def call(data):
        data = jnp.asarray(data, dtype=jnp.uint8)
        squeeze = data.ndim == 2
        if squeeze:
            data = data[None]
        out = fn(data)
        return out[0] if squeeze else out

    return call


# ---------------------------------------------------------------------------
# CRC32C (ceph raw-seed semantics, seed 0) over rows
#
# Whole-tile fold + cross-tile Horner recurrence: each grid step folds a
# (rows_block, tile) slab with the tile-length message matrix on the MXU
# (bits stay in VMEM), then advances the running 32-bit state:
#     acc <- A_tile @ acc  ^  fold(tile)            (all GF(2))
# The j grid axis is sequential ("arbitrary") so the recurrence is legal;
# rows are independent and parallel.
# ---------------------------------------------------------------------------

CRC_ROWS_BLOCK = 32       # rows per grid cell; bits slab = rows*8*tile int8
CRC_TILE = 8192           # bytes per fold step; foldT = (8*tile, 32) int8


def _crc_kernel(foldT_ref, adv_ref, lanemask_ref, x_ref, out_ref, acc_ref,
                *, ntiles: int):
    j = pl.program_id(1)
    x = x_ref[:]                                    # (NC, TILE) uint8
    # Lane-expand x 8-fold with whole-tile copies (jnp.repeat along the
    # minor axis is unsupported for 8-bit): copy c holds bit c of every
    # byte, i.e. bit (byte j, bit b) lands at lane b*TILE + j.  The fold
    # matrix columns are permuted to this copy-major order host-side.
    brep = jnp.concatenate([x] * 8, axis=1)         # (NC, 8*TILE)
    bits = ((brep & lanemask_ref[:]) != 0).astype(jnp.int8)
    r = jax.lax.dot_general(
        bits, foldT_ref[:],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    ) & 1                                           # (NC, 32)

    @pl.when(j == 0)
    def _():
        acc_ref[:] = r

    @pl.when(j > 0)
    def _():
        adv = jax.lax.dot_general(
            acc_ref[:].astype(jnp.int8), adv_ref[:],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        acc_ref[:] = (adv + r) & 1

    @pl.when(j == ntiles - 1)
    def _():
        out_ref[:] = acc_ref[:]


@functools.lru_cache(maxsize=64)
def _crc_call(L: int, tile: int, rows_block: int, interpret: bool):
    from . import crc32c as crc_mod

    ntiles = L // tile
    fold = crc_mod.message_matrix(tile)             # cols: byte j, bit b
    # permute columns to the kernel's copy-major lane order b*tile + j
    perm = np.empty(8 * tile, dtype=np.int64)
    lanes = np.arange(8 * tile)
    perm[(lanes % 8) * tile + lanes // 8] = lanes
    foldT = jnp.asarray(fold[:, perm].T.astype(np.int8))
    # advance the running state over one tile of message: the state from
    # earlier bytes sits `tile` zero-bytes further from the end
    advT = jnp.asarray(crc_mod.advance_matrix(tile).T.astype(np.int8))
    lanemask = jnp.asarray(np.tile(
        (1 << (np.arange(8 * tile) // tile)).astype(np.uint8)[None, :],
        (rows_block, 1)))
    kernel = functools.partial(_crc_kernel, ntiles=ntiles)
    weights32 = jnp.asarray([1 << i for i in range(32)], dtype=jnp.uint32)

    @jax.jit
    def run(rows):                                  # (N, L) uint8
        N = rows.shape[0]
        pad = (-N) % rows_block
        if pad:
            rows = jnp.concatenate(
                [rows, jnp.zeros((pad, L), jnp.uint8)], axis=0)
        NP = N + pad
        bits_out = pl.pallas_call(
            kernel,
            grid=(NP // rows_block, ntiles),
            in_specs=[
                pl.BlockSpec((8 * tile, 32), lambda n, j: (0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((32, 32), lambda n, j: (0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((rows_block, 8 * tile), lambda n, j: (0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((rows_block, tile), lambda n, j: (n, j),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((rows_block, 32), lambda n, j: (n, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((NP, 32), jnp.int32),
            scratch_shapes=[pltpu.VMEM((rows_block, 32), jnp.int32)],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "arbitrary")),
            interpret=interpret,
        )(foldT, advT, lanemask, rows)
        crcs = jnp.sum(bits_out.astype(jnp.uint32) * weights32[None, :],
                       axis=-1, dtype=jnp.uint32)
        return crcs[:N]

    return run


def make_crc_fn(L: int, tile: int = CRC_TILE,
                rows_block: int = CRC_ROWS_BLOCK,
                interpret: bool | None = None):
    """Jitted CRC32C (seed 0): rows (N, L) uint8 -> (N,) uint32."""
    t = _pick_tile(L, tile)
    if t is None:
        raise ValueError(f"L={L} not tileable (needs multiple of 128)")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _crc_call(L, t, rows_block, interpret)


def make_encode_crc_fn(matrix: np.ndarray, L: int,
                       interpret: bool | None = None):
    """fn(data (B, k, L)) -> (parity (B, m, L), crcs (B, k+m) uint32).

    Pallas encode + pallas CRC composed under one jit: parity stays in
    HBM between the two kernels; the scrub CRCs cover data and parity
    chunks (HashInfo semantics, osd/ECUtil.cc:140).
    """
    m, k = np.asarray(matrix).shape
    enc = make_encode_fn(matrix, L, interpret=interpret)
    crc = make_crc_fn(L, interpret=interpret)

    @jax.jit
    def run(data):
        B = data.shape[0]
        parity = enc(data)
        # CRC data and parity slabs separately: a concatenate would
        # copy every byte through HBM again just to flatten the rows
        dcrc = crc(data.reshape(B * k, L)).reshape(B, k)
        pcrc = crc(parity.reshape(B * m, L)).reshape(B, m)
        crcs = jnp.concatenate([dcrc, pcrc], axis=1)
        return parity, crcs

    def call(data):
        data = jnp.asarray(data, dtype=jnp.uint8)
        squeeze = data.ndim == 2
        if squeeze:
            data = data[None]
        parity, crcs = run(data)
        return (parity[0], crcs[0]) if squeeze else (parity, crcs)

    return call
