"""Compression plugin framework (compressor/Compressor.{h,cc} +
CompressionPlugin.h analog).

The reference registers snappy/zlib plugins through the generic
PluginRegistry and BlueStore/messenger call compress()/decompress()
through the abstract Compressor.  Here plugins are stdlib-backed
(zlib, bz2, lzma — snappy is not in this image) behind the same
factory surface; blobs are framed with a one-byte algorithm tag +
raw length so decompression is self-describing and a corrupted or
unknown frame errors instead of passing through.
"""

from __future__ import annotations

import bz2
import lzma
import struct
import zlib

_HDR = struct.Struct("<BQ")      # algorithm id, raw length


class CompressorError(Exception):
    pass


class Compressor:
    """One algorithm; subclasses provide _compress/_decompress."""

    NAME = "none"
    ID = 0

    def compress(self, data: bytes) -> bytes:
        data = bytes(data)
        return _HDR.pack(self.ID, len(data)) + self._compress(data)

    def decompress(self, blob: bytes) -> bytes:
        if len(blob) < _HDR.size:
            raise CompressorError("short compressed blob")
        alg, raw_len = _HDR.unpack_from(blob)
        if alg != self.ID:
            raise CompressorError(
                f"blob is {_by_id(alg)}, not {self.NAME}")
        try:
            out = self._decompress(blob[_HDR.size:])
        except Exception as e:
            raise CompressorError(f"decompress failed: {e}") from e
        if len(out) != raw_len:
            raise CompressorError(
                f"length mismatch: {len(out)} != {raw_len}")
        return out

    def _compress(self, data: bytes) -> bytes:
        raise NotImplementedError

    def _decompress(self, data: bytes) -> bytes:
        raise NotImplementedError


class ZlibCompressor(Compressor):
    NAME, ID = "zlib", 1

    def _compress(self, data: bytes) -> bytes:
        return zlib.compress(data, level=1)

    def _decompress(self, data: bytes) -> bytes:
        return zlib.decompress(data)


class Bz2Compressor(Compressor):
    NAME, ID = "bz2", 2

    def _compress(self, data: bytes) -> bytes:
        return bz2.compress(data, compresslevel=1)

    def _decompress(self, data: bytes) -> bytes:
        return bz2.decompress(data)


class LzmaCompressor(Compressor):
    NAME, ID = "lzma", 3

    def _compress(self, data: bytes) -> bytes:
        return lzma.compress(data, preset=0)

    def _decompress(self, data: bytes) -> bytes:
        return lzma.decompress(data)


_PLUGINS: dict[str, type[Compressor]] = {
    c.NAME: c for c in (ZlibCompressor, Bz2Compressor, LzmaCompressor)}


def _by_id(alg_id: int) -> str:
    for cls in _PLUGINS.values():
        if cls.ID == alg_id:
            return cls.NAME
    return f"unknown({alg_id})"


def create(name: str) -> Compressor:
    """Compressor::create: factory by algorithm name."""
    cls = _PLUGINS.get(name)
    if cls is None:
        raise CompressorError(
            f"unknown compressor {name!r}; have {sorted(_PLUGINS)}")
    return cls()


def decompress_any(blob: bytes) -> bytes:
    """Decompress a self-describing frame regardless of algorithm."""
    if len(blob) < _HDR.size:
        raise CompressorError("short compressed blob")
    alg, _ = _HDR.unpack_from(blob)
    return create(_by_id(alg)).decompress(blob)


def algorithms() -> list[str]:
    return sorted(_PLUGINS)
