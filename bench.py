"""North-star benchmark: EC encode throughput, TPU vs host baseline.

Reproduces the reference's ceph_erasure_code_benchmark semantics
(/root/reference/src/test/erasure-code/ceph_erasure_code_benchmark.cc:180
— time N iterations of encode over in-memory buffers, report GB/s) for
the BASELINE.md config #2: reed_sol_van k=8 m=3, 1 MiB chunks.

Like the CPU reference (whose buffers sit in RAM), the TPU measurement
encodes device-resident batches; dispatches are pipelined the way the
OSD's ECBackend would stream stripe batches.  Prints ONE JSON line:
{"metric", "value", "unit", "vs_baseline"} — value is TPU encode GB/s,
vs_baseline the ratio to the host-CPU oracle in the same process.
"""

from __future__ import annotations

import json
import time

import numpy as np


def main() -> None:
    import jax
    import jax.numpy as jnp

    from ceph_tpu.erasure.registry import registry
    from ceph_tpu.ops import ec_kernels, gf

    k, m = 8, 3
    chunk = 1 << 20          # 1 MiB chunks (BASELINE config #2)
    batch = 32               # stripes per dispatch
    depth = 10               # dispatches in flight
    rng = np.random.default_rng(7)
    data = rng.integers(0, 256, size=(batch, k, chunk), dtype=np.uint8)

    matrix = gf.reed_sol_van_matrix(k, m)
    fn = ec_kernels.make_codec_fn(matrix)
    x = jax.device_put(jnp.asarray(data))
    jax.block_until_ready(fn(x))     # compile + warm

    def tpu_round():
        t0 = time.perf_counter()
        outs = [fn(x) for _ in range(depth)]
        jax.block_until_ready(outs)
        return time.perf_counter() - t0

    tpu_times = [tpu_round() for _ in range(3)]
    t_tpu = min(tpu_times) / depth           # seconds per batch

    # host baseline: native C++ region kernels (the ISA-L stand-in),
    # falling back to the numpy oracle where no compiler exists
    host = registry.factory("jerasure", {"k": str(k), "m": str(m),
                                         "technique": "reed_sol_van"})
    host.encode_chunks(data[0])              # warm tables
    t0 = time.perf_counter()
    host_parity = host.encode_chunks(data[0])
    t_host = (time.perf_counter() - t0)      # seconds per stripe

    # correctness gate: benchmark numbers only count if outputs match
    np.testing.assert_array_equal(np.asarray(fn(x))[0], host_parity)

    gbs_tpu = data.nbytes / t_tpu / 1e9
    gbs_host = (data.nbytes / batch) / t_host / 1e9
    print(json.dumps({
        "metric": "ec_encode_rs_k8m3_1MiB",
        "value": round(gbs_tpu, 3),
        "unit": "GB/s",
        "vs_baseline": round(gbs_tpu / gbs_host, 2),
    }))


if __name__ == "__main__":
    main()
