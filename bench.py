"""North-star benchmark: EC encode/decode throughput, TPU vs host AVX2.

Reproduces the reference's ceph_erasure_code_benchmark semantics
(/root/reference/src/test/erasure-code/ceph_erasure_code_benchmark.cc:180
— time encode/decode over in-memory buffers, report GB/s) across the
BASELINE.md config matrix, with the bench.sh-style sweep rows
(qa/workunits/erasure-code/bench.sh:58-60 format) on stderr and ONE JSON
line on stdout for the driver.

Methodology notes (all measured on this rig, see git history):
  * the axon tunnel syncs cost ~90 ms and repeated identical dispatches
    can be served from a relay cache, so inputs are GENERATED ON DEVICE
    from a per-dispatch seed and timing uses the two-point slope
    (T(n2)-T(n1))/(n2-n1) with one witness fetch per run — no transfer
    cost, no cache hits, no fixed-latency pollution;
  * the device-input-generation cost is measured separately and
    subtracted (reported numbers are kernel-only, like the reference's
    in-RAM buffers);
  * the host baseline is the native AVX2 pshufb kernel
    (ceph_tpu/native/gf.cc ceph_tpu_gf_encode_avx2) — the same
    algorithm as ISA-L's gf_Nvect_dot_prod_avx2, the strongest host
    path this machine has (1 core).

Primary metric (BASELINE config #2, north star): fused encode +
per-chunk CRC32C for reed_sol k=8,m=3 on 1 MiB chunks, batched; the
criterion is >= 4x the host AVX2 encode GB/s.

E2e methodology (changed with the cross-op pipeline): the PIPELINED
e2e row — many op-sized encode+CRC submissions riding the shared
ceph_tpu.ops.pipeline dispatcher (coalesced shape-bucketed
mega-batches, overlapped dispatches, depth >= 4) — is the primary e2e
metric; the serial row is kept as the baseline it amortizes away.
Crossover rows score the device path at its AMORTIZED (overlapped)
per-op cost, matching how TpuBackend's measured routing now scores it.

`--smoke`: tiny sizes, CPU-safe, no rig assumptions — run by tier-1
CI so bench bit-rot is caught before the slow rig run.  It forces the
8-device CPU mesh, so sharded placement, mega-batch splitting and the
one-chip quarantine drill are exercised (and oracle-checked) on every
CI pass.

`--multichip`: chip-count sweep (1/2/4/8 lanes as available) through
the production pipeline — aggregate GB/s, per-chip GB/s and scaling
efficiency per count; also runs inside the full bench when more than
one device is visible.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def make_gen(batch: int, k: int, chunk: int):
    import jax
    import jax.numpy as jnp

    def gen(seed):
        base = jax.lax.broadcasted_iota(jnp.uint32,
                                        (batch, k, chunk // 4), 2)
        mixed = ((base * jnp.uint32(2654435761)
                  + seed * jnp.uint32(40503)) ^ (base >> 13))
        return jax.lax.bitcast_convert_type(mixed, jnp.uint8).reshape(
            batch, k, chunk)

    return gen


def slope_time(fn, n1: int = 8, n2: int = 40, reps: int = 5) -> float:
    """Per-dispatch seconds via two-point slope with single sync.

    The relay adds ~100 ms of fixed sync latency with tens of ms of
    jitter, so the spread (n2-n1) must dwarf it and early runs (cold
    relay) are discarded.
    """
    import jax.numpy as jnp

    total = 4 + reps * (n1 + n2)
    seeds = [jnp.uint32(s) for s in range(total)]
    off = [0]

    def run_n(n):
        o = off[0]
        off[0] += n
        t0 = time.perf_counter()
        outs = [fn(seeds[o + i]) for i in range(n)]
        np.asarray(jnp.stack(outs))
        return time.perf_counter() - t0

    run_n(2)                       # compile
    run_n(2)                       # relay warm
    pairs = []
    for _ in range(reps):
        t1 = run_n(n1)
        t2 = run_n(n2)
        pairs.append((t2 - t1) / (n2 - n1))
    pairs.sort()
    return max(pairs[len(pairs) // 2], 1e-9)   # median


def bench_host_encode(matrix: np.ndarray, chunk: int) -> float:
    """Host AVX2 GB/s for one stripe of `chunk`-sized chunks."""
    from ceph_tpu import native
    from ceph_tpu.ops import gf as gf_mod

    k = matrix.shape[1]
    rng = np.random.default_rng(11)
    data = rng.integers(0, 256, size=(k, chunk), dtype=np.uint8)
    if native.available():
        enc = lambda: native.gf_encode(matrix, data)
    else:
        enc = lambda: gf_mod.encode_np(matrix, data)
    enc()
    n = max(3, int(2e8 // data.nbytes))
    t0 = time.perf_counter()
    for _ in range(n):
        enc()
    t = (time.perf_counter() - t0) / n
    return data.nbytes / t / 1e9


def bench_config2(results: list, rows: list) -> dict:
    """North-star config: reed_sol k=8,m=3, fused encode+crc, sweep."""
    import jax
    import jax.numpy as jnp

    from ceph_tpu.ops import gf, pallas_ec

    k, m = 8, 3
    matrix = gf.reed_sol_van_matrix(k, m)
    host_gbs = bench_host_encode(matrix, 1 << 20)
    log(f"host AVX2 encode k={k} m={m} 1MiB: {host_gbs:.2f} GB/s")

    fast = bool(os.environ.get("BENCH_FAST"))
    sizes = [1 << 20] if fast else [4096, 1 << 16, 1 << 20, 1 << 22]
    primary = None
    for chunk in sizes:
        # ~256 MB per dispatch so the marginal device time (~10 ms)
        # dwarfs relay jitter in the slope
        batch = max(1, (1 << 28) // (k * chunk))
        useful = batch * k * chunk
        gen = make_gen(batch, k, chunk)

        @jax.jit
        def gen_only(seed):
            return gen(seed).sum(dtype=jnp.uint32)

        t_gen = slope_time(gen_only)

        fused = pallas_ec.make_encode_crc_fn(matrix, chunk)

        @jax.jit
        def fused_s(seed):
            _p, c = fused(gen(seed))
            return c.sum(dtype=jnp.uint32)

        t = slope_time(fused_s)
        enc_gbs = useful / max(t - t_gen, 1e-9) / 1e9

        # decode: reconstruct all k data chunks from k survivors
        # (m erasures, the worst case) — matrix is (k, k)
        gen_full = gf.systematic_generator(matrix, k)
        present = list(range(m, k + m))[:k]
        dmat = gf.decode_matrix(gen_full, k, present)
        dec = pallas_ec.make_encode_fn(dmat, chunk)

        @jax.jit
        def dec_s(seed):
            return dec(gen(seed)).sum(dtype=jnp.uint32)

        t = slope_time(dec_s)
        dec_gbs = useful / max(t - t_gen, 1e-9) / 1e9

        rows.append(("encode", "tpu", k, m, chunk, enc_gbs))
        rows.append(("decode", "tpu", k, m, chunk, dec_gbs))
        log(f"tpu fused encode+crc k={k} m={m} {chunk}B: "
            f"{enc_gbs:.2f} GB/s   decode: {dec_gbs:.2f} GB/s")
        if chunk == 1 << 20:
            primary = {"enc": enc_gbs, "dec": dec_gbs, "host": host_gbs}
    return primary


def bench_e2e(rows: list) -> dict:
    """Transfer-INCLUSIVE numbers: host bytes -> device -> fused
    encode+crc -> parity + crcs fetched back to host (the path an OSD
    write takes when parity must reach the store).  Quantifies the
    axon-tunnel transfer cost the kernel-only rows exclude — and why
    the measured host/device router can prefer the host for
    store-bound writes on this rig.

    Two rows: strictly serial (put, compute, fetch) and double-
    buffered (the NEXT batch's device_put is enqueued before blocking
    on the current batch's fetch, so upload rides behind compute +
    the previous fetch — jax async dispatch does the overlap)."""
    import jax

    from ceph_tpu.ops import gf, pallas_ec

    k, m = 8, 3
    chunk = 1 << 20
    batch = 1                       # 8 MiB payload per round trip: the
    matrix = gf.reed_sol_van_matrix(k, m)   # tunnel moves ~10-30 MB/s
    fused = pallas_ec.make_encode_crc_fn(matrix, chunk)
    rng = np.random.default_rng(3)
    nbuf = 6
    bufs = [rng.integers(0, 256, size=(batch, k, chunk),
                         dtype=np.uint8) for _ in range(1 + 2 + nbuf)]
    useful = batch * k * chunk

    def once(buf):
        dev = jax.device_put(buf)
        parity, crcs = fused(dev)
        return np.asarray(parity), np.asarray(crcs)

    once(bufs[0])                   # compile + warm
    t0 = time.perf_counter()
    n = 2
    for i in range(n):
        once(bufs[1 + i])           # distinct buffers: no relay cache
    t = (time.perf_counter() - t0) / n
    gbs = useful / t / 1e9
    rows.append(("encode-e2e", "tpu", k, m, chunk, gbs))
    log(f"tpu e2e (host->device->fused->host) k={k} m={m} 1MiB: "
        f"{gbs:.2f} GB/s")

    # overlapped: pipeline depth 2 over nbuf distinct buffers
    obufs = bufs[3:]
    t0 = time.perf_counter()
    pending = fused(jax.device_put(obufs[0]))
    for i in range(1, nbuf):
        nxt = jax.device_put(obufs[i])     # enqueued pre-block
        np.asarray(pending[0]), np.asarray(pending[1])
        pending = fused(nxt)
    np.asarray(pending[0]), np.asarray(pending[1])
    t = (time.perf_counter() - t0) / nbuf
    overlap_gbs = useful / t / 1e9
    rows.append(("encode-e2e-overlap", "tpu", k, m, chunk,
                 overlap_gbs))
    # overlap efficiency: how much of the serial round trip the
    # double-buffer window actually hides.  BENCH_r05 showed the two
    # rows EXACTLY equal — a dead overlap window reading as a healthy
    # one — so a ratio ~1.0 now fails loudly instead of passing silent.
    efficiency = overlap_gbs / max(gbs, 1e-9)
    if efficiency <= 1.02:
        log(f"tpu e2e OVERLAP WINDOW DEAD: overlapped == serial "
            f"({efficiency:.2f}x) — uploads are not riding behind "
            f"compute/fetch; the async dispatch overlap is not "
            f"happening on this rig")
    log(f"tpu e2e OVERLAPPED (double-buffered x{nbuf}): "
        f"{overlap_gbs:.2f} GB/s ({efficiency:.2f}x serial)")
    return {"serial": gbs, "overlap": overlap_gbs,
            "overlap_efficiency": round(efficiency, 3)}


def bench_host_path_breakdown(rows: list, payload_mib: int = 4,
                              nreps: int = 5) -> dict:
    """Per-hop host-path cost of one client EC write, measured with
    the REAL primitives the cluster path runs — so the next bottleneck
    is a named hop with a copy count, not one opaque e2e number:

      stripe  client striping: rope wrap + zero-copy extent slicing
              (client/striper.py math + utils/bufferlist.py)
      frame   message framing: MOSDOp.encode_iov — denc header + the
              payload riding as out-of-band CTM2 segments
      fanout  EC encode + CRC + shard-major layout via osd/ecutil.py
              (host codec path: native AVX2 + hardware CRC)
      store   k+m shard-view transaction applies into a MemStore

    Reports per-hop wall µs and the payload bytes each hop COPIED
    (runtime copy-audit deltas — the number this PR drives to ~2
    materializations per write: encode staging + shard layout)."""
    from ceph_tpu.client.striper import Layout, file_to_extents
    from ceph_tpu.erasure.registry import registry
    from ceph_tpu.osd import ecutil
    from ceph_tpu.osd.messages import MOSDOp
    from ceph_tpu.store.memstore import MemStore
    from ceph_tpu.store.objectstore import Transaction
    from ceph_tpu.utils import copyaudit
    from ceph_tpu.utils.bufferlist import BufferList, wrap_payload

    k, m = 8, 3
    nbytes = payload_mib << 20
    rng = np.random.default_rng(31)
    payload = rng.integers(0, 256, size=nbytes, dtype=np.uint8).tobytes()
    codec = registry.factory("jerasure", {"k": str(k), "m": str(m),
                                          "technique": "reed_sol_van"})
    sinfo = ecutil.StripeInfo(k, 1 << 16)
    layout = Layout(stripe_unit=1 << 20, stripe_count=4,
                    object_size=1 << 22)
    store = MemStore()
    store.apply_transaction(Transaction().create_collection("bench"))
    out: dict = {}

    def hop(name, fn):
        fn()                                   # warm
        before = copyaudit.snapshot()
        t0 = time.perf_counter()
        for _ in range(nreps):
            fn()
        us = (time.perf_counter() - t0) / nreps * 1e6
        after = copyaudit.snapshot()
        copied = (after["ec_host_copy_bytes"]
                  - before["ec_host_copy_bytes"]) // nreps
        ncopies = (after["host_copies"] - before["host_copies"]) / nreps
        out[name] = {"us": round(us, 1), "bytes_copied": int(copied),
                     "copies": round(ncopies, 1),
                     "gbs": round(nbytes / max(us, 1e-3) / 1e3, 3)}
        rows.append((f"hostpath-{name}", "host", k, m, nbytes,
                     out[name]["gbs"]))

    def do_stripe():
        rope = BufferList(wrap_payload(payload))
        for ext in file_to_extents(layout, 0, len(rope)):
            rope.slice(ext.logical_offset, ext.length)

    def do_frame():
        MOSDOp(tid=1, pgid="1.0", oid="o",
               ops=[("writefull", memoryview(payload))], epoch=1,
               snapc=None, snapid=None).encode_iov(seq=1)

    shards_box: list = []

    def do_fanout():
        shards_box.clear()
        shards, crcs = ecutil.encode_object_ex(codec, sinfo, payload)
        shards_box.append(shards)

    def do_store():
        txn = Transaction()
        for shard, data in enumerate(shards_box[0]):
            txn.truncate("bench", f"o.s{shard}", 0)
            txn.write("bench", f"o.s{shard}", 0, data)
        store.apply_transaction(txn)

    hop("stripe", do_stripe)
    hop("frame", do_frame)
    hop("fanout", do_fanout)
    hop("store", do_store)
    total_us = sum(h["us"] for h in out.values())
    out["total"] = {
        "us": round(total_us, 1),
        "bytes_copied": sum(h["bytes_copied"] for h in out.values()
                            if "us" in h),
        "gbs": round(nbytes / max(total_us, 1e-3) / 1e3, 3),
        "payload_bytes": nbytes,
    }
    log("host path breakdown (%d MiB write): " % payload_mib
        + " | ".join(
            f"{name} {h['us']:.0f}us"
            f" ({h['bytes_copied'] >> 10} KiB copied)"
            for name, h in out.items() if name != "total")
        + f" | total {out['total']['gbs']:.3f} GB/s")
    return out


def _warm_pipeline_codec(codec, k: int, chunk: int, max_batch: int,
                         window: float = 240.0,
                         devices=None) -> bool:
    """Pre-compile the fused fn for every power-of-two stripe bucket
    the pipeline can coalesce into — on every device lane the
    multichip placement can pick (readiness is per chip) — so the
    timed run never falls back to host on a cold shape."""
    matrix = codec.coding_matrix
    buckets = []
    b = 1
    while b <= max_batch:
        buckets.append(b)
        b *= 2
    if devices is None:
        devices = [None]
    want = [(b, d) for b in buckets for d in devices]
    end = time.time() + window
    ready: set = set()
    while time.time() < end and len(ready) < len(want):
        for b, dev in want:
            if (b, dev) in ready:
                continue
            fn = codec.backend.fused_fn_if_ready(matrix, (b, k, chunk),
                                                 dev)
            if fn is not None:
                ready.add((b, dev))
        # permanent compile failures are negative-cached by the
        # backend; don't spin the whole window on a box that can
        # never warm (broken device / backend init failure)
        failed_shapes = {rk[1] for rk in
                         list(getattr(codec.backend, "_warm_failed",
                                      ()))}
        if any((b, k, chunk) in failed_shapes for b in buckets):
            log("warm-up: device compile failed, proceeding on host")
            break
        time.sleep(0.25)
    return len(ready) == len(want)


def bench_e2e_pipelined(rows: list, chunk: int = 1 << 20,
                        nops: int = 32, per_op: int = 1,
                        depth: int = 4, max_batch: int = 4,
                        warm_window: float = 240.0,
                        routing: str = "measured") -> dict:
    # 32 ops coalescing into 4-stripe (32 MiB) mega-batches -> 8
    # dispatches, so the depth-4 overlap window actually fills
    """The primary e2e metric: `nops` concurrent op-sized fused
    encode+CRC submissions ride the shared cross-op pipeline — they
    coalesce into shape-bucketed mega-batches and issue as overlapped
    dispatches (queue depth >= `depth`).  Transfer-INCLUSIVE: host
    bytes in, parity + CRCs back, distinct buffers per op (no relay
    cache).

    routing="measured" (default) runs the PRODUCTION path: the
    backend's measured host/device routing sends every dispatch to
    whichever plane its amortized sec/byte EMA says is faster on THIS
    rig (the host drain is the zero-copy native AVX2 encode + hardware
    CRC path) — so the number is what the cluster write path actually
    achieves, not a forced-device showcase.  routing="device" pins
    host_cutover=1, the old behavior, kept for device-plane tracking.
    """
    import jax

    from ceph_tpu.erasure.registry import registry
    from ceph_tpu.ops import pipeline as ec_pipeline

    k, m = 8, 3
    profile = {"k": str(k), "m": str(m), "technique": "reed_sol_van"}
    if routing == "device":
        profile["host_cutover"] = "1"
    codec = registry.factory("tpu", profile)
    ec_pipeline.configure(depth=depth, coalesce_wait=0.002,
                          max_batch=max_batch)
    # readiness is keyed per (shape, device): warm every lane the
    # pipeline's placement can pick, or the timed run silently
    # measures host dispatches against cold per-device keys
    warmed = _warm_pipeline_codec(codec, k, chunk, max_batch,
                                  window=warm_window,
                                  devices=list(jax.devices()))
    if not warmed and routing == "device":
        log("pipelined e2e: device fns not warm in time; results "
            "may include host-path dispatches")
    rng = np.random.default_rng(13)
    ops = [rng.integers(0, 256, size=(per_op, k, chunk),
                        dtype=np.uint8) for _ in range(nops)]
    useful = nops * per_op * k * chunk
    if routing == "measured":
        # prime the routing EMAs AT THE COALESCED BUCKET the timed run
        # will dispatch (per_op stripes x max_batch ops): the router
        # needs one host sample + two device probes per size bucket
        # before it settles, and a short run would otherwise be
        # dominated by the probe cost instead of the settled plane
        probe = rng.integers(0, 256,
                             size=(per_op * max_batch, k, chunk),
                             dtype=np.uint8)
        for _ in range(4):
            codec.encode_stripes_with_crcs(probe)
    stats0 = ec_pipeline.stats()
    t0 = time.perf_counter()
    handles = [codec.encode_stripes_with_crcs_async(op) for op in ops]
    for h in handles:
        # collect the way the OSD fan-out does (ecutil.EncodeHandle):
        # parts, not the joined (S, k+m, L) array — the write path
        # never materializes that intermediate anymore
        if hasattr(h, "result_parts"):
            h.result_parts()
        else:
            h.result()
    t = time.perf_counter() - t0
    gbs = useful / t / 1e9
    stats1 = ec_pipeline.stats()
    dispatches = stats1["dispatches"] - stats0["dispatches"]
    dev = stats1["dev_dispatches"] - stats0["dev_dispatches"]
    h2d = stats1["bytes_h2d"] - stats0["bytes_h2d"]
    d2h = stats1["bytes_d2h"] - stats0["bytes_d2h"]
    label = "encode-e2e-pipelined" if routing == "measured" \
        else "encode-e2e-pipelined-dev"
    rows.append((label, "tpu", k, m, chunk, gbs))
    log(f"tpu e2e PIPELINED/{routing} ({nops} ops x "
        f"{per_op * k * chunk >> 20}"
        f"MiB, depth={depth}, max_batch={max_batch}): {gbs:.3f} GB/s "
        f"({dispatches} dispatches, {dev} on device, "
        f"mean batch {nops * per_op / max(dispatches, 1):.1f} stripes, "
        f"{h2d >> 20} MiB h2d / {d2h >> 20} MiB d2h — parity-only "
        f"readback)")
    return {"gbs": gbs, "dispatches": dispatches,
            "dev_dispatches": dev, "bytes_h2d": h2d, "bytes_d2h": d2h,
            "routing": routing,
            "crossover": codec.backend.crossover_estimate()}


def bench_transfer_breakdown(rows: list, chunk: int = 1 << 20,
                             reps: int = 3) -> dict:
    """Per-phase split of the transfer-inclusive path — H2D upload,
    on-device fused compute, parity+CRC readback — each timed alone,
    so the remaining e2e time is attributable to a specific phase
    instead of one opaque number.  Distinct buffers per dispatch (no
    relay cache)."""
    import jax

    from ceph_tpu.ops import ec_kernels, gf

    k, m = 8, 3
    batch = 1
    matrix = gf.reed_sol_van_matrix(k, m)
    fused = ec_kernels.make_encode_crc_fn(matrix, chunk)
    rng = np.random.default_rng(17)
    bufs = [rng.integers(0, 256, size=(batch, k, chunk),
                         dtype=np.uint8) for _ in range(reps + 1)]
    useful = batch * k * chunk
    # warm/compile
    warm = jax.device_put(bufs[0])
    p, c = fused(warm)
    np.asarray(p), np.asarray(c)
    # h2d: upload alone
    t0 = time.perf_counter()
    devs = []
    for b in bufs[1:]:
        d = jax.device_put(b)
        d.block_until_ready()
        devs.append(d)
    t_h2d = (time.perf_counter() - t0) / reps
    # compute: device-resident inputs, outputs blocked on device
    outs = []
    t0 = time.perf_counter()
    for d in devs:
        p, c = fused(d)
        c.block_until_ready()
        p.block_until_ready()
        outs.append((p, c))
    t_comp = (time.perf_counter() - t0) / reps
    # d2h: fetch the already-computed parity + CRCs
    d2h_bytes = 0
    t0 = time.perf_counter()
    for p, c in outs:
        pn, cn = np.asarray(p), np.asarray(c)
        d2h_bytes = pn.nbytes + cn.nbytes
    t_d2h = (time.perf_counter() - t0) / reps
    out = {
        "h2d_gbs": round(useful / max(t_h2d, 1e-9) / 1e9, 4),
        "compute_gbs": round(useful / max(t_comp, 1e-9) / 1e9, 4),
        "d2h_gbs": round(useful / max(t_d2h, 1e-9) / 1e9, 4),
        "d2h_bytes_per_dispatch": int(d2h_bytes),
        "d2h_parity_only": bool(
            d2h_bytes == ec_kernels.encode_readback_bytes(
                batch, k, m, chunk)),
    }
    for phase, gbs in (("h2d", out["h2d_gbs"]),
                       ("compute", out["compute_gbs"]),
                       ("d2h", out["d2h_gbs"])):
        rows.append((f"phase-{phase}", "tpu", k, m, chunk, gbs))
    log(f"transfer breakdown (payload {useful >> 20} MiB): "
        f"h2d {out['h2d_gbs']:.3f} GB/s | compute "
        f"{out['compute_gbs']:.3f} GB/s | d2h {out['d2h_gbs']:.3f} "
        f"GB/s ({d2h_bytes} B/dispatch, parity-only="
        f"{out['d2h_parity_only']})")
    return out


def _warm_mesh_codec(codec, k: int, chunk: int, shapes,
                     plane_key: tuple, window: float,
                     donate: bool = False) -> bool:
    """Pre-compile the mesh-sharded fused fn for every batch shape the
    timed run can coalesce into (the mesh executable is specialized
    per shape AND per mesh plane)."""
    matrix = codec.coding_matrix
    want = {(S, k, chunk) for S in shapes}
    ready: set = set()
    end = time.time() + window
    while time.time() < end and len(ready) < len(want):
        for shape in want - ready:
            if codec.backend.mesh_fn_if_ready(
                    matrix, shape, plane_key, donate) is not None:
                ready.add(shape)
        time.sleep(0.25)
    return len(ready) == len(want)


def _host_oracle_encode_crc(codec, batch: np.ndarray):
    """The independent host plane (native matmul + table CRC, no jax)
    mesh results are checked bit-exactly against."""
    from ceph_tpu.ops import crc32c as crc_mod

    matrix = codec.coding_matrix
    parity = np.asarray(codec._host_backend().apply_bytes(matrix, batch))
    B, k, L = batch.shape
    m = parity.shape[1]
    crcs = np.empty((B, k + m), dtype=np.uint32)
    crcs[:, :k] = crc_mod.crc32c_batch(
        batch.reshape(B * k, L)).reshape(B, k)
    crcs[:, k:] = crc_mod.crc32c_batch(
        parity.reshape(B * m, L)).reshape(B, m)
    return parity, crcs


def bench_multichip(rows: list, chip_counts=(1, 2, 4, 8),
                    chunk: int = 1 << 20, nops: int = 32,
                    per_op: int = 2, depth: int = 2,
                    max_batch: int = 8,
                    warm_window: float = 240.0) -> dict:
    """Multichip mode: the SAME pipelined op stream at 1/2/4/8 dispatch
    lanes, reporting aggregate GB/s, per-chip GB/s and scaling
    efficiency (aggregate(n) / (n * aggregate(1))) for BOTH placement
    modes — row-split (independent per-lane batches) and mesh dispatch
    (one batch shard_mapped across the lanes) — plus the
    object-larger-than-one-lane's-staging-budget case only the mesh
    can dispatch at all.  Placement is the production pipeline's —
    this measures the op path end to end (transfer-inclusive,
    distinct buffers), not an isolated kernel sweep."""
    import jax

    from ceph_tpu.erasure.registry import registry
    from ceph_tpu.ops import pipeline as ec_pipeline

    k, m = 8, 3
    avail = len(jax.devices())
    counts = sorted({c for c in chip_counts if c <= avail})
    if not counts:
        counts = [avail]
    log(f"multichip: {avail} visible devices, sweeping {counts}")
    codec = registry.factory("tpu", {"k": str(k), "m": str(m),
                                     "technique": "reed_sol_van",
                                     "host_cutover": "1"})
    rng = np.random.default_rng(29)
    ops = [rng.integers(0, 256, size=(per_op, k, chunk),
                        dtype=np.uint8) for _ in range(nops)]
    useful = nops * per_op * k * chunk
    results: dict = {}
    base_per_chip = None
    pipe = ec_pipeline.get()
    for n in counts:
        pipe.reset_devices(device_shards=n)
        ec_pipeline.configure(depth=depth, coalesce_wait=0.002,
                              max_batch=max_batch, split_min=per_op,
                              mesh_min_bytes=0)
        warmed = _warm_pipeline_codec(
            codec, k, chunk, max_batch, window=warm_window,
            devices=list(jax.devices())[:n])
        if not warmed:
            log(f"multichip n={n}: device fns not fully warm; "
                "results may include host dispatches")
        stats0 = ec_pipeline.stats()
        t0 = time.perf_counter()
        handles = [codec.encode_stripes_with_crcs_async(op)
                   for op in ops]
        for h in handles:
            h.result()
        t = time.perf_counter() - t0
        gbs = useful / t / 1e9
        stats1 = ec_pipeline.stats()
        dev = stats1["dev_dispatches"] - stats0["dev_dispatches"]
        splits = stats1["split_dispatches"] - \
            stats0["split_dispatches"]
        lanes_used = sum(1 for d in stats1["devices"].values()
                         if d["dispatches"] > 0)
        if base_per_chip is None:
            base_per_chip = gbs / n
        eff = gbs / (n * base_per_chip) if base_per_chip else 1.0
        results[str(n)] = {
            "aggregate_gbs": round(gbs, 3),
            "per_chip_gbs": round(gbs / n, 3),
            "scaling_efficiency": round(eff, 3),
            "dev_dispatches": dev, "split_dispatches": splits,
            "lanes_used": lanes_used,
            # mesh row (filled below for n >= 2; a 1-chip "mesh" is
            # not a mesh — the keys still always emit)
            "mesh_aggregate_gbs": None,
            "mesh_scaling_efficiency": None,
            "mesh_dispatches": 0,
        }
        rows.append((f"encode-multichip-x{n}", "tpu", k, m, chunk,
                     gbs))
        log(f"multichip n={n}: {gbs:.3f} GB/s aggregate "
            f"({gbs / n:.3f}/chip, eff {eff:.2f}, {dev} dev "
            f"dispatches, {splits} splits, {lanes_used} lanes used)")
        if n < 2:
            continue
        # mesh row: same op stream, every coalesced batch over the
        # lane budget so placement picks mesh dispatch
        ec_pipeline.configure(mesh_min_bytes=1)
        plane_key = (tuple(jax.devices()[:n]), 1, n)
        shapes = {min(s, max_batch) for s in
                  range(per_op, max_batch + 1, per_op)} | {per_op}
        mwarmed = _warm_mesh_codec(codec, k, chunk, shapes,
                                   plane_key, warm_window)
        if not mwarmed:
            log(f"multichip n={n}: mesh fns not fully warm; mesh row "
                "may include row-split dispatches")
        mstats0 = ec_pipeline.stats()
        t0 = time.perf_counter()
        handles = [codec.encode_stripes_with_crcs_async(op)
                   for op in ops]
        for h in handles:
            h.result()
        t = time.perf_counter() - t0
        mesh_gbs = useful / t / 1e9
        mstats1 = ec_pipeline.stats()
        mesh_disp = mstats1["mesh_dispatches"] - \
            mstats0["mesh_dispatches"]
        meff = mesh_gbs / (n * base_per_chip) if base_per_chip else 1.0
        results[str(n)].update({
            "mesh_aggregate_gbs": round(mesh_gbs, 3),
            "mesh_scaling_efficiency": round(meff, 3),
            "mesh_dispatches": mesh_disp,
        })
        ec_pipeline.configure(
            mesh_min_bytes=ec_pipeline.DEFAULT_MESH_MIN_BYTES)
        rows.append((f"encode-mesh-x{n}", "tpu", k, m, chunk,
                     mesh_gbs))
        log(f"multichip n={n} MESH: {mesh_gbs:.3f} GB/s aggregate "
            f"(eff {meff:.2f} vs 1-chip row-split, {mesh_disp} mesh "
            f"dispatches)")
    results["mega_object"] = _bench_mesh_mega(
        codec, k, chunk, counts[-1], warm_window, rows)
    pipe.reset_devices(device_shards=None)
    ec_pipeline.configure(
        mesh_min_bytes=ec_pipeline.DEFAULT_MESH_MIN_BYTES)
    return results


def _bench_mesh_mega(codec, k: int, chunk: int, n: int,
                     warm_window: float, rows: list) -> dict:
    """The previously-undispatchable case: ONE batch whose staged
    bytes exceed a single lane's budget.  Row-split placement cannot
    serve it on a real HBM-bounded chip; the mesh shard_maps it and
    the output is checked bit-exactly against the native host plane."""
    import jax

    from ceph_tpu.ops import pipeline as ec_pipeline

    out = {"bytes": None, "gbs": None, "mesh_dispatches": 0,
           "ok": False}
    if n < 2:
        return out
    budget = max(4 * k * chunk, 1 << 20)        # the lane budget
    S = max(2, (3 * budget) // (k * chunk))     # 3x over it
    nbytes = S * k * chunk
    out["bytes"] = nbytes
    out["lane_budget_bytes"] = budget
    ec_pipeline.get().reset_devices(device_shards=n)
    ec_pipeline.configure(mesh_min_bytes=budget)
    plane_key = (tuple(jax.devices()[:n]), 1, n)
    if not _warm_mesh_codec(codec, k, chunk, {S}, plane_key,
                            warm_window):
        log(f"mesh mega-object: fn not warm in {warm_window:.0f}s, "
            "skipping")
        return out
    rng = np.random.default_rng(31)
    batch = rng.integers(0, 256, size=(S, k, chunk), dtype=np.uint8)
    stats0 = ec_pipeline.stats()
    t0 = time.perf_counter()
    allc, crcs = codec.encode_stripes_with_crcs_async(batch).result(600)
    t = time.perf_counter() - t0
    stats1 = ec_pipeline.stats()
    out["mesh_dispatches"] = stats1["mesh_dispatches"] - \
        stats0["mesh_dispatches"]
    out["gbs"] = round(nbytes / t / 1e9, 3)
    parity_o, crcs_o = _host_oracle_encode_crc(codec, batch)
    out["ok"] = bool(out["mesh_dispatches"] >= 1
                     and np.array_equal(allc[:, k:], parity_o)
                     and np.array_equal(allc[:, :k], batch)
                     and np.array_equal(crcs, crcs_o))
    ec_pipeline.configure(
        mesh_min_bytes=ec_pipeline.DEFAULT_MESH_MIN_BYTES)
    rows.append((f"encode-mesh-mega-x{n}", "tpu", k,
                 codec.coding_matrix.shape[0], chunk,
                 out["gbs"] or 0.0))
    log(f"mesh mega-object: {nbytes >> 20} MiB batch (lane budget "
        f"{budget >> 20} MiB) -> {out['gbs']} GB/s over {n} chips, "
        f"{out['mesh_dispatches']} mesh dispatches, ok={out['ok']}")
    return out


def bench_crossover(rows: list) -> dict:
    """Measured host<->device crossover for the router's two workload
    classes (erasure/matrix_codec.py TpuBackend routing), END-TO-END:
    both sides are charged the FULL work an EC write/scrub needs from
    one payload — store-writable parity AND the per-chunk CRC32C scrub
    checksums HashInfo persists — not just the matmul.

      * store-bound (OSD write): host = native AVX2 encode + hardware
        CRC over zero-copy shard views (the post-zero-copy host plane:
        no concat, no per-shard bytes); device = put + fused
        encode+CRC + parity-only fetch, amortized over `depth`
        overlapped dispatches (how the pipeline actually runs it).
      * scrub-bound: the same host work; device = the witness kernel —
        parity never leaves the chip, only 4*(k+m) CRC bytes return.

    Emits one row per (mode, payload) and returns the smallest payload
    where the amortized device path wins each mode (None = the host
    plane wins end-to-end at every swept size on this rig — on a
    CPU-only or tunnel-relay rig that is the EXPECTED truth, and the
    measured router will keep every dispatch on the host plane)."""
    import jax

    from ceph_tpu import native
    from ceph_tpu.ops import ec_kernels, gf, pallas_ec

    probe = np.zeros((1, 8, 64), dtype=np.uint8)
    if native.gf_encode_batch(
            gf.reed_sol_van_matrix(8, 3), probe) is None:
        # needs the CPython ext (ctypes-only builds return None here)
        log("crossover: native batch kernel unavailable, skipping")
        return {"store": None, "scrub": None}
    k, m = 8, 3
    chunk = 1 << 20
    depth = 4
    matrix = gf.reed_sol_van_matrix(k, m)
    try:
        # hand-tiled pallas kernel on real TPU; XLA-fused elsewhere
        # (pallas is TPU-only and absent in some jax versions, and its
        # failure only surfaces at first-call compile) — the sweep must
        # MEASURE on every rig, not die into nulls
        fused = pallas_ec.make_encode_crc_fn(matrix, chunk)
        _p, _c = fused(jax.device_put(
            np.zeros((1, k, chunk), dtype=np.uint8)))
        np.asarray(_p)
    except Exception:
        fused = ec_kernels.make_encode_crc_fn(matrix, chunk)
    witness = ec_kernels.make_encode_crc_witness_fn(matrix, chunk)
    rng = np.random.default_rng(7)
    results = {"store": {}, "scrub": {}}
    log(f"crossover: host CRC tier = "
        f"{'hardware crc32 instruction' if native.crc32c_hw() else 'sliced-by-8 tables'}")

    for batch in (1, 2, 4):
        payload = batch * k * chunk
        data = rng.integers(0, 256, size=(batch, k, chunk),
                            dtype=np.uint8)
        bufs = [rng.integers(0, 256, size=(batch, k, chunk),
                             dtype=np.uint8) for _ in range(depth)]

        def host_store():
            # the real host write plane: encode, then CRC the data
            # shards IN PLACE (views, no concat) + the parity shards
            parity = native.gf_encode_batch(matrix, data)
            dcrcs = native.crc32c_batch(0, data.reshape(batch * k,
                                                        chunk))
            pcrcs = native.crc32c_batch(0, parity.reshape(batch * m,
                                                          chunk))
            return parity, dcrcs, pcrcs

        host_scrub = host_store     # scrub needs the same CRC set

        def dev_store_amortized():
            # depth overlapped put+fused dispatches; fetch in issue
            # order so upload of n+1.. rides behind fetch of n
            pend = [fused(jax.device_put(b)) for b in bufs]
            return [(np.asarray(p), np.asarray(c)) for p, c in pend]

        def dev_scrub_amortized():
            # witness kernel: parity never leaves the device, only
            # the 4*(k+m)-byte CRCs return per dispatch
            pend = [witness(jax.device_put(b)) for b in bufs]
            return [np.asarray(c) for c in pend]

        for mode, host_fn, dev_fn in (
                ("store", host_store, dev_store_amortized),
                ("scrub", host_scrub, dev_scrub_amortized)):
            host_fn()
            t0 = time.perf_counter()
            host_fn()
            t_host = time.perf_counter() - t0
            dev_fn()                      # warm/compile
            t0 = time.perf_counter()
            dev_fn()
            t_dev = (time.perf_counter() - t0) / depth
            hg = payload / t_host / 1e9
            dg = payload / t_dev / 1e9
            results[mode][payload] = (hg, dg)
            rows.append((f"xover-{mode}-host", "native", k, m,
                         payload, hg))
            rows.append((f"xover-{mode}-dev", "tpu", k, m,
                         payload, dg))
            log(f"crossover {mode} payload={payload >> 20}MiB: "
                f"host {hg:.2f} GB/s vs device (amortized x{depth}) "
                f"{dg:.2f} GB/s")

    out = {}
    for mode, pts in results.items():
        win = [p for p, (hg, dg) in sorted(pts.items()) if dg > hg]
        out[mode] = win[0] if win else None
    log(f"crossover: device wins store-bound at {out['store']} B, "
        f"scrub-bound at {out['scrub']} B (None = host always wins)")
    return out


def bench_other_configs(rows: list) -> None:
    """Configs #1, #3, #4, #5 via the plugin registry codecs."""
    from ceph_tpu.erasure.registry import registry

    configs = [
        # (plugin, profile, chunk, stripe batch): batch=1 is the
        # per-op latency form; the batched row is the whole-object
        # dispatch the OSD's ECUtil path actually issues (one native/
        # device call per object, osd/ecutil.py)
        ("jerasure", {"k": "2", "m": "1", "technique": "reed_sol_van"},
         4096, 1),
        ("jerasure", {"k": "2", "m": "1", "technique": "reed_sol_van"},
         4096, 128),
        ("jerasure", {"k": "6", "m": "3", "technique": "cauchy_good",
                      "packetsize": "32"}, 1 << 20, 1),
        ("shec", {"k": "8", "m": "4", "c": "3"}, 1 << 20, 1),
        ("lrc", {"k": "4", "m": "2", "l": "3"}, 1 << 20, 1),
    ]
    for plugin, profile, chunk, batch in configs:
        try:
            codec = registry.factory(plugin, dict(profile))
            k = codec.get_data_chunk_count()
            km = codec.get_chunk_count()
            rng = np.random.default_rng(5)
            shape = (batch, k, chunk) if batch > 1 else (k, chunk)
            data = rng.integers(0, 256, size=shape, dtype=np.uint8)
            for _ in range(3):
                codec.encode_chunks(data)      # warm
            n = max(3, int(1e8 // data.nbytes))
            t0 = time.perf_counter()
            for _ in range(n):
                codec.encode_chunks(data)
            t = (time.perf_counter() - t0) / n
            gbs = data.nbytes / t / 1e9
            desc = profile.get("technique", plugin)
            if batch > 1:
                desc += f"_x{batch}"
            rows.append(("encode", desc, k, km - k, chunk, gbs))
            log(f"{plugin} {profile} batch={batch}: "
                f"encode {gbs:.2f} GB/s")
        except Exception as e:
            log(f"{plugin} {profile}: SKIP ({e})")


def _load_cluster(conf_extra: dict | None = None):
    """A small real cluster (1 mon / 3 osds) + an EC pool wired for
    the serving plane: device-routed encodes (host_cutover=1) so the
    HBM stripe cache populates on the CPU mesh exactly as it would on
    a real chip."""
    from ceph_tpu.utils.config import Config
    from ceph_tpu.vstart import MiniCluster
    conf = Config({
        "mon_tick_interval": 0.5,
        "osd_heartbeat_interval": 0.5,
        "osd_heartbeat_grace": 8.0,
        "mon_osd_min_down_reporters": 2,
        "mon_osd_down_out_interval": 5.0,
        **(conf_extra or {})})
    return MiniCluster(num_mons=1, num_osds=3, conf=conf).start()


def _settle_pool(rados, name: str, profile_name: str,
                 window: float = 60.0):
    rados.create_ec_pool(
        name, profile_name,
        {"plugin": "tpu", "k": 2, "m": 1, "host_cutover": 1},
        pg_num=8)
    io = rados.open_ioctx(name)
    end = time.time() + window
    while True:
        try:
            io.write_full("settle", b"s")
            return io
        except Exception:
            if time.time() > end:
                raise
            time.sleep(0.3)


def _frontdoor_doors(cluster, bucket: str = "s3bench") -> dict:
    """Open every front door on one cluster: a raw rados pool, S3
    over a real RGW gateway (its own zone pool), CephFS through a
    live MDS, and an RBD image mapped slot-per-object.  Returns the
    ``ioctxs`` map LoadGen drives plus the gateway/image handles the
    caller owns."""
    from ceph_tpu.client import CephFSDoor, RGWDoor
    from ceph_tpu.fs import CephFS, FsError
    from ceph_tpu.rbd import RBD, Image
    from ceph_tpu.tools.loadgen import RBDImageDoor
    rados = cluster.client()
    rados.create_pool("doors", pg_num=4)
    rados_io = rados.open_ioctx("doors")
    end = time.time() + 60
    while True:
        try:
            rados_io.write_full("settle", b"s")
            break
        except Exception:
            if time.time() > end:
                raise
            cluster.tick(0.3)
    cluster.start_mds("a")
    fs = CephFS(cluster.client("client.fsbench"))
    end = time.time() + 60
    while True:
        try:
            fs.mount(timeout=10.0)
            break
        except FsError:
            if time.time() > end:
                raise
            cluster.tick(0.5)
    slot = 1 << 16
    rados.create_pool("rbdbench", pg_num=4)
    rbd_io = rados.open_ioctx("rbdbench")
    RBD(rbd_io).create("img", size=16 * slot, order=16)
    img = Image(rbd_io, "img")
    gw = cluster.start_rgw(data_pool="zone_a")
    return {
        "ioctxs": {
            "doors": rados_io,
            "s3": RGWDoor(f"http://127.0.0.1:{gw.port}",
                          bucket=bucket),
            "fs": CephFSDoor(fs, root="/bench"),
            "rbd": RBDImageDoor(img, slot_bytes=slot),
        },
        "image": img, "gateway": gw,
    }


def _frontdoor_tenants(duration: float,
                       rates=(40.0, 18.0, 10.0, 16.0)) -> list:
    """One seeded mixed-door tenant set: rados carries appends and
    deletes, the HTTP doors own their resends via retry_window, RBD
    rides slot-mapped full writes."""
    from ceph_tpu.tools.loadgen import TenantSpec
    r0, r1, r2, r3 = rates
    return [
        TenantSpec("doors", rate=r0, duration=duration, obj_count=32,
                   read_frac=0.5, append_frac=0.2, delete_frac=0.15,
                   payload=8192, door="rados", retry_window=45.0),
        TenantSpec("s3", rate=r1, duration=duration, obj_count=16,
                   read_frac=0.5, delete_frac=0.15, payload=4096,
                   door="s3", retry_window=45.0, max_workers=16),
        TenantSpec("fs", rate=r2, duration=duration, obj_count=12,
                   read_frac=0.5, delete_frac=0.1, payload=4096,
                   door="cephfs", retry_window=45.0, max_workers=8),
        TenantSpec("rbd", rate=r3, duration=duration, obj_count=16,
                   read_frac=0.5, payload=4096, door="rbd",
                   retry_window=45.0, max_workers=8),
    ]


def bench_load(rows: list, fast: bool = False) -> dict:
    """The serving-plane rows: a seeded OPEN-LOOP multi-tenant load
    harness (ceph_tpu/tools/loadgen.py) against a real in-process
    cluster — per-pool p50/p99/p999 latency, goodput and queue depth
    under arrival-rate-controlled mixed traffic — plus the
    cache-served read row: client EC reads served from the HBM stripe
    cache vs the same reads through the object store."""
    from ceph_tpu.ops import hbm_cache
    from ceph_tpu.tools.loadgen import LoadGen, TenantSpec
    from ceph_tpu.utils import copyaudit
    duration = 3.0 if fast else 8.0
    cluster = _load_cluster()
    try:
        rados = cluster.client()
        io_hot = _settle_pool(rados, "load-hot", "loadp1")
        io_bulk = _settle_pool(rados, "load-bulk", "loadp2")
        tenants = [
            TenantSpec("load-hot", rate=40 if fast else 80,
                       duration=duration, obj_count=32, zipf_s=1.2,
                       read_frac=0.7, payload=16384,
                       append_frac=0.1),
            TenantSpec("load-bulk", rate=20 if fast else 40,
                       duration=duration, obj_count=32, zipf_s=0.8,
                       read_frac=0.2, payload=65536),
        ]
        gen = LoadGen(tenants, seed=0x10AD)
        copy0 = copyaudit.snapshot()
        report = gen.run({"load-hot": io_hot, "load-bulk": io_bulk})
        copy1 = copyaudit.snapshot()
        reads = max(1, copy1["reads"] - copy0["reads"])
        copies_per_read = (copy1["read_copies"]
                           - copy0["read_copies"]) / reads
        for pool, st in report["pools"].items():
            rows.append((f"load-{pool}-p99", "cluster", 2, 1,
                         0, st["p99_ms"]))
        log(f"load harness (seed {gen.seed:#x}, {duration:.0f}s): "
            + " | ".join(
                f"{p} p50={st['p50_ms']}ms p99={st['p99_ms']}ms "
                f"p999={st['p999_ms']}ms good={st['goodput_gbs']}GB/s "
                f"qmax={st['queue_depth_max']}"
                for p, st in report["pools"].items())
            + f" | copies/read={copies_per_read:.2f}")
        # -- cache-served reads vs store-path reads -------------------
        payload = 1 << 19                # 512 KiB: shard-copy bound
        nobj = 4 if fast else 8
        body = {i: _load_body(i, payload) for i in range(nobj)}
        cache = hbm_cache.get()
        # populate until probe reads of the WHOLE hot set serve from
        # the cache (each lane's fused fn warms in the background; a
        # write that lands on a still-cold lane host-serves uncached)
        end = time.time() + (45 if fast else 120)
        while time.time() < end:
            for i in range(nobj):
                io_hot.write_full(f"hot{i:02d}", body[i])
            s0 = cache.stats()["read_bytes_served"]
            for i in range(nobj):
                io_hot.read(f"hot{i:02d}")
            if cache.stats()["read_bytes_served"] - s0 >= \
                    nobj * payload:
                break
            time.sleep(0.3)
        cached_entries = cache.stats()["entries"]
        reps = 3 if fast else 6
        s0 = cache.stats()
        t0 = time.perf_counter()
        for _ in range(reps):
            for i in range(nobj):
                assert len(io_hot.read(f"hot{i:02d}")) == payload
        t_cache = time.perf_counter() - t0
        s1 = cache.stats()
        served = s1["read_bytes_served"] - s0["read_bytes_served"]
        read_cache_gbs = (reps * nobj * payload / t_cache / 1e9
                          if served > 0 else None)
        # same reads with the cache disabled: the store path
        # (per-shard reads + reassembly) serves every byte.  The
        # cache is PROCESS-WIDE: restore the prior capacity even when
        # a read throws, or every later bench section runs cacheless
        prior_capacity = cache.capacity
        hbm_cache.configure(0)
        try:
            t0 = time.perf_counter()
            for _ in range(reps):
                for i in range(nobj):
                    assert len(io_hot.read(f"hot{i:02d}")) == payload
            t_store = time.perf_counter() - t0
        finally:
            hbm_cache.configure(prior_capacity)
        read_store_gbs = reps * nobj * payload / t_store / 1e9
        if read_cache_gbs:
            rows.append(("read-cache", "hbm", 2, 1, payload,
                         read_cache_gbs))
        rows.append(("read-store", "host", 2, 1, payload,
                     read_store_gbs))
        log(f"cache-served reads: {read_cache_gbs and round(read_cache_gbs, 3)} GB/s "
            f"({served >> 20} MiB off-chip-served, {cached_entries} "
            f"entries) vs store path {read_store_gbs:.3f} GB/s")
        # -- every front door, one seeded schedule --------------------
        # the same open-loop generator, fanned across rados + S3 +
        # CephFS + RBD against this same cluster: per-door p50/p99/
        # p999 + goodput as comparable rows, stale oracle armed
        fd = _frontdoor_doors(cluster)
        fd_gen = LoadGen(_frontdoor_tenants(3.0 if fast else 6.0),
                         seed=0xD004)
        fd_report = fd_gen.run(fd["ioctxs"], verify=True)
        fd["image"].close()
        doors = fd_report["doors"]
        for d, st in sorted(doors.items()):
            rows.append((f"door-{d}-p99", "cluster", 2, 1, 0,
                         st["p99_ms"]))
        log(f"front doors (seed {fd_gen.seed:#x}): " + " | ".join(
            f"{d} p50={st['p50_ms']}ms p99={st['p99_ms']}ms "
            f"p999={st['p999_ms']}ms good={st['goodput_gbs']}GB/s"
            for d, st in sorted(doors.items())))
        return {
            "p50_ms": report["p50_ms"], "p99_ms": report["p99_ms"],
            "p999_ms": report["p999_ms"],
            "goodput_gbs": report["goodput_gbs"],
            "pools": report["pools"],
            "host_copies_per_read": round(copies_per_read, 2),
            "read_cache_gbs": read_cache_gbs and round(
                read_cache_gbs, 4),
            "read_store_gbs": round(read_store_gbs, 4),
            "cache_read_bytes_served": served,
            "doors": doors,
            "door_errors": sum(st["errors"] for st in doors.values()),
            "door_stale_reads": sum(st["stale_reads"]
                                    for st in doors.values()),
        }
    finally:
        cluster.stop()


def bench_conn_scaling(rows: list, fast: bool = False) -> dict:
    """The connection-COUNT axis: the same seeded conn storm
    (tools/loadgen.run_conn_storm) at 64/256/1024 concurrent client
    sessions against a fresh cluster per messenger stack.  The row
    the async serving plane exists for: the blocking stack pins a
    messenger thread per session (peak threads linear in sessions),
    the epoll stack multiplexes every session onto the fixed
    ``ms_async_op_threads`` pool (peak bounded by the DRIVER pool,
    flat in sessions) — while p99/goodput at high fan-in must not
    pay for it."""
    from ceph_tpu.tools.loadgen import run_conn_storm
    counts = (16, 64) if fast else (64, 256, 1024)
    per: dict[str, dict[int, dict]] = {}
    for ms_type in ("blocking", "async"):
        cluster = _load_cluster({"ms_type": ms_type})
        try:
            per[ms_type] = {}
            for n in counts:
                res = run_conn_storm(cluster, n, seed=0xC099,
                                     pool=f"connstorm{n}")
                per[ms_type][n] = res
                rows.append((f"conn-{ms_type}-{n}-p99", "cluster",
                             2, 1, 0, res["p99_ms"]))
                log(f"conn {ms_type} n={n}: p99={res['p99_ms']}ms "
                    f"good={res['goodput_mbs']}MB/s threads "
                    f"{res['base_threads']}->{res['peak_threads']}"
                    f"->{res['quiesce_threads']} fds "
                    f"{res['base_fds']}->{res['peak_fds']}"
                    f"->{res['quiesce_fds']} errors={res['errors']}")
        finally:
            cluster.stop()
    lo, hi = counts[0], counts[-1]
    bgrow = {n: per["blocking"][n]["peak_threads"]
             - per["blocking"][n]["base_threads"] for n in counts}
    agrow = {n: per["async"][n]["peak_threads"]
             - per["async"][n]["base_threads"] for n in counts}
    # flat-vs-linear: async peak growth is bounded by the storm's
    # own 32-thread driver pool at EVERY session count (sessions
    # multiplex onto the fixed epoll workers), while blocking pays
    # ~1 messenger thread per session on top of the same driver —
    # its growth at the top count carries the session count itself
    flat_ok = bool(max(agrow.values()) <= 32 + 8
                   and bgrow[hi] >= hi)
    if fast:
        # tiny fast-mode counts measure scheduler noise, not fan-in:
        # sanity-bound the tail instead of ranking the stacks
        tail_ok = bool(
            per["async"][hi]["p99_ms"]
            <= per["blocking"][hi]["p99_ms"] * 1.5 + 150.0)
    else:
        # the contract: async no worse at the low count, and no
        # worse at the top count where blocking drags >1000 threads
        # through the scheduler
        tail_ok = bool(
            per["async"][lo]["p99_ms"]
            <= per["blocking"][lo]["p99_ms"] * 1.25
            and per["async"][hi]["p99_ms"]
            <= per["blocking"][hi]["p99_ms"])
    errors = sum(per[s][n]["errors"] for s in per for n in counts)
    leaks = sum(
        max(0, per[s][n]["quiesce_threads"]
            - per[s][n]["base_threads"])
        + max(0, per[s][n]["quiesce_fds"] - per[s][n]["base_fds"])
        for s in per for n in counts)
    out = {
        "conn_scaling_counts": list(counts),
        "conn_scaling_blocking_peak_threads": [bgrow[n]
                                               for n in counts],
        "conn_scaling_async_peak_threads": [agrow[n] for n in counts],
        "conn_scaling_blocking_p99_ms": [
            per["blocking"][n]["p99_ms"] for n in counts],
        "conn_scaling_async_p99_ms": [
            per["async"][n]["p99_ms"] for n in counts],
        "conn_scaling_blocking_goodput_mbs": [
            per["blocking"][n]["goodput_mbs"] for n in counts],
        "conn_scaling_async_goodput_mbs": [
            per["async"][n]["goodput_mbs"] for n in counts],
        "conn_scaling_event_workers": per["async"][lo]["event_workers"],
        "conn_scaling_errors": errors,
        "conn_scaling_leaks": leaks,
        "conn_scaling_flat_ok": flat_ok,
        "conn_scaling_tail_ok": tail_ok,
        "conn_scaling_ok": bool(flat_ok and tail_ok and errors == 0
                                and leaks == 0),
    }
    log(f"conn scaling: async threads {[agrow[n] for n in counts]} "
        f"vs blocking {[bgrow[n] for n in counts]} over "
        f"{list(counts)} sessions, flat_ok={flat_ok}, "
        f"tail_ok={tail_ok}, ok={out['conn_scaling_ok']}")
    return out


def _load_body(seed: int, size: int) -> bytes:
    from ceph_tpu.tools.loadgen import _payload_bytes
    return _payload_bytes(seed, size)


def _storm_pools(cluster, names=("gold", "bulk"), window: float = 60.0):
    """Replicated size-3/min_size-2 pools for the storm drills: the
    cluster keeps serving (and acking) with one OSD dead, which is
    the whole point of serve-during-repair."""
    rados = cluster.client()
    ios = {}
    for name in names:
        rados.create_pool(name, pg_num=8, size=3, min_size=2)
        ios[name] = rados.open_ioctx(name)
    end = time.time() + window
    while True:
        try:
            for io in ios.values():
                io.write_full("settle", b"s")
            return ios
        except Exception:
            if time.time() > end:
                raise
            time.sleep(0.3)


def bench_recovery_slo(fast: bool = False) -> dict:
    """The serve-during-repair SLO sweep: the SAME seeded OSD-kill
    storm under multi-tenant load, once per ``osd_qos_recovery``
    setting, reporting the reserved pool's p50/p99/p999 DURING the
    storm next to the recovery completion wall time — the knob's
    client-latency-vs-repair-time trade-off as two measured numbers
    per setting instead of folklore.  The gold pool carries a
    dmClock reservation; recovery rides the @recovery class."""
    from ceph_tpu.tools.loadgen import TenantSpec, run_recovery_storm
    # aggressive repair (weight 3, uncapped) vs limit-throttled
    # repair (weight 1, ~hard grant cap): the first finishes recovery
    # sooner at more client-tail cost, the second inverts it
    settings = ("0:3:0", "0:1:60")
    duration = 6.0 if fast else 10.0
    sweep = []
    for setting in settings:
        cluster = _load_cluster({
            "osd_qos_recovery": setting,
            "osd_pool_qos_gold": "60:4:0",
            "objecter_op_timeout": 60.0,
        })
        try:
            ios = _storm_pools(cluster)
            tenants = [
                TenantSpec("gold", rate=25 if fast else 40,
                           duration=duration, obj_count=24,
                           zipf_s=1.1, read_frac=0.6, payload=16384),
                TenantSpec("bulk", rate=15 if fast else 25,
                           duration=duration, obj_count=24,
                           zipf_s=0.9, read_frac=0.3, payload=32768),
            ]
            res = run_recovery_storm(
                cluster, ios, tenants, seed=0x5708,
                kill_at=duration * 0.25,
                revive_after=duration * 0.2)
            gold_storm = res["storm"].get("gold", {})
            sweep.append({
                "osd_qos_recovery": setting,
                "storm_window_s": res["storm_window_s"],
                "recovery_wall_s": res["recovery_wall_s"],
                "gold_storm_p50_ms": gold_storm.get("p50_ms"),
                "gold_storm_p99_ms": gold_storm.get("p99_ms"),
                "gold_storm_p999_ms": gold_storm.get("p999_ms"),
                "gold_full_p99_ms":
                    res["report"]["pools"]["gold"]["p99_ms"],
                "errors": res["errors"],
                "stale_reads": res["stale_reads"],
                "blocked_ops": res["recovery_blocked_ops"],
                "unblocked_ops": res["recovery_unblocked_ops"],
                "prio_promotions": res["recovery_prio_promotions"],
                "recovery_qos_grants": res["recovery_qos_grants"],
                "recovery_qos_throttle_stalls":
                    res["recovery_qos_throttle_stalls"],
                "ledger_ok": res["ledger_ok"],
            })
            log(f"recovery-slo @ {setting}: gold storm "
                f"p99={gold_storm.get('p99_ms')}ms, recovery "
                f"{res['recovery_wall_s']}s, blocked="
                f"{res['recovery_blocked_ops']}, errors="
                f"{res['errors']}, stale={res['stale_reads']}, "
                f"ledger_ok={res['ledger_ok']}")
        finally:
            cluster.stop()
    return {"sweep": sweep}


def _measure_peering_ms(cluster, pgid, reps: int = 3,
                        timeout: float = 30.0) -> float | None:
    """Wall time of one full peering round on the pg's primary (force
    inactive, queue the round, wait active) — min over `reps` so
    scheduler noise doesn't masquerade as scaling."""
    m = cluster.leader().osdmon.osdmap
    _up, acting = m.pg_to_up_acting_osds(pgid)
    primary = next(o for o in acting if o >= 0)
    osd = cluster.osds[primary]
    pg = osd.get_pg(pgid)
    best = None
    for _ in range(reps):
        with pg.lock:
            pg.active = False
        t0 = time.perf_counter()
        osd.queue_peering(pgid)
        end = time.time() + timeout
        while not pg.active and time.time() < end:
            time.sleep(0.002)
        if not pg.active:
            return None
        dt = (time.perf_counter() - t0) * 1000.0
        best = dt if best is None else min(best, dt)
    return best


def bench_peering(rows: list, fast: bool = False) -> dict:
    """Log-authoritative peering acceptance sweep: peering exchanges
    LOG BOUNDS only, so a full peering round's wall time must stay
    FLAT as per-PG object count grows 10x-100x; and recovery is
    log-divergence-driven, so recovery_bytes must track injected
    divergence (entries), never pg size.  Seeded and deterministic in
    structure (the only noise is scheduler jitter, absorbed by
    min-of-reps)."""
    from ceph_tpu.store.objectstore import Transaction
    counts = (8, 80, 800) if fast else (16, 160, 1600)
    reps = 3 if fast else 5
    cluster = _load_cluster()
    out: dict = {}
    try:
        rados = cluster.client()
        rados.create_pool("peer-scale", pg_num=1, size=3, min_size=2)
        io = rados.open_ioctx("peer-scale")
        end = time.time() + 60
        while True:
            try:
                io.write_full("settle", b"s")
                break
            except Exception:
                if time.time() > end:
                    raise
                time.sleep(0.3)
        m = cluster.leader().osdmon.osdmap
        pgid = m.object_to_pg(io.pool_id, "settle")
        written = 0
        for label, count in zip(("1x", "10x", "100x"), counts):
            while written < count:
                io.write_full(f"o{written:06d}", b"x" * 64)
                written += 1
            ms = _measure_peering_ms(cluster, pgid, reps=reps)
            out[f"peering_ms_at_{label}"] = (round(ms, 2)
                                             if ms is not None
                                             else None)
            rows.append((f"peering-{label}", "cluster", 0, 0,
                         count, ms or -1.0))
            log(f"peering @ {count} objects: {out[f'peering_ms_at_{label}']} ms")
        # -- recovery_bytes ∝ divergence drill -------------------------
        K, dpay = 6, 1 << 15
        bodies = {i: _load_body(1000 + i, dpay) for i in range(K)}
        for i in range(K):
            io.write_full(f"div{i:03d}", bodies[i])
        m = cluster.leader().osdmon.osdmap
        _up, acting = m.pg_to_up_acting_osds(pgid)
        primary = next(o for o in acting if o >= 0)
        victim = next(o for o in acting if o >= 0 and o != primary)
        vosd = cluster.osds[victim]
        vpg = vosd.get_pg(pgid)
        # wait until the victim actually holds all K, then regress it
        end = time.time() + 30
        while time.time() < end:
            if all(vosd.store.exists(vpg.cid, f"div{i:03d}")
                   for i in range(K)):
                break
            time.sleep(0.1)
        with vpg.lock:
            for i in range(K):
                oid = f"div{i:03d}"
                try:
                    vosd.store.apply_transaction(
                        Transaction().remove(vpg.cid, oid))
                except Exception:
                    pass
                vpg.pglog.objects.pop(oid, None)
                vpg.pglog.entries = [e for e in vpg.pglog.entries
                                     if e["oid"] != oid]
        posd = cluster.osds[primary]
        b0 = posd._perf_dump()["osd"]["recovery_bytes"]
        posd.get_pg(pgid).start_peering()
        end = time.time() + 60
        healed = False
        while time.time() < end and not healed:
            healed = all(
                vosd.store.exists(vpg.cid, f"div{i:03d}")
                and bytes(vosd.store.read(vpg.cid, f"div{i:03d}"))
                == bodies[i] for i in range(K))
            time.sleep(0.2)
        b1 = posd._perf_dump()["osd"]["recovery_bytes"]
        delta = b1 - b0
        out["recovery_divergent_entries"] = K
        out["recovery_bytes_total"] = delta
        out["recovery_bytes_per_divergent_entry"] = (
            round(delta / K, 1) if healed and K else None)
        # proportionality: bytes track the K divergent entries, never
        # the pg's full object population
        out["recovery_proportional_ok"] = bool(
            healed and delta <= 3 * K * dpay)
        log(f"divergence drill: healed={healed}, {delta} recovery "
            f"bytes for {K} divergent entries "
            f"(payload {dpay}; proportional_ok="
            f"{out['recovery_proportional_ok']})")
        return out
    finally:
        cluster.stop()


def bench_smoke() -> None:
    """Tier-1 CI mode: tiny sizes, CPU-safe, no rig assumptions.

    Forces an 8-device CPU mesh (same as the test harness) BEFORE jax
    initializes, so the run exercises the production multichip path:
    sharded placement across lanes, mega-batch splitting, and the
    one-chip quarantine + redrain drill — all checked bit-exactly
    against the host oracle codec.  Emits ONE JSON line, so bench
    bit-rot (import errors, API drift, a wedged pipeline, a placement
    regression) fails fast in CI instead of surfacing on the slow rig
    run.
    """
    from __graft_entry__ import force_host_device_count

    os.environ["JAX_PLATFORMS"] = "cpu"
    # REPLACE any inherited device-count flag (a driver exporting
    # count=1 would otherwise silently shrink the mesh and fail the
    # sharded/split gates on healthy code)
    force_host_device_count(os.environ, 8)

    import jax

    from ceph_tpu.erasure.registry import registry
    from ceph_tpu.ops import gf
    from ceph_tpu.ops import pipeline as ec_pipeline
    from ceph_tpu.utils import faults

    k, m, chunk = 8, 3, 4096
    nops = 16
    n_dev = len(jax.devices())
    matrix = gf.reed_sol_van_matrix(k, m)
    host_gbs = bench_host_encode(matrix, chunk)
    codec = registry.factory("tpu", {"k": str(k), "m": str(m),
                                     "technique": "reed_sol_van",
                                     "host_cutover": "1"})
    oracle = registry.factory("jerasure", {"k": str(k), "m": str(m),
                                           "technique": "reed_sol_van"})
    ec_pipeline.configure(depth=4, coalesce_wait=0.001, max_batch=8,
                          split_min=2)
    warmed = _warm_pipeline_codec(codec, k, chunk, 8, window=90.0,
                                  devices=list(jax.devices()))
    rng = np.random.default_rng(23)
    ops = [rng.integers(0, 256, size=(1, k, chunk), dtype=np.uint8)
           for _ in range(nops)]
    useful = nops * k * chunk
    bytes0 = ec_pipeline.stats()
    # serial: one sync round trip per op
    t0 = time.perf_counter()
    serial_out = [codec.encode_stripes_with_crcs(op) for op in ops]
    serial_gbs = useful / max(time.perf_counter() - t0, 1e-9) / 1e9
    # pipelined: all ops in flight at once — coalesced mega-batches
    # place/split across every lane of the forced 8-device mesh
    t0 = time.perf_counter()
    handles = [codec.encode_stripes_with_crcs_async(op) for op in ops]
    pipe_out = [h.result(60) for h in handles]
    pipe_gbs = useful / max(time.perf_counter() - t0, 1e-9) / 1e9
    # correctness gate: both paths bit-exact vs the host oracle
    ok = True
    for op, (allc_s, crcs_s), (allc_p, crcs_p) in zip(
            ops, serial_out, pipe_out):
        allc_o, crcs_o = oracle.encode_stripes_with_crcs(op)
        ok = ok and np.array_equal(allc_s, allc_o) \
            and np.array_equal(crcs_s, crcs_o) \
            and np.array_equal(allc_p, allc_o) \
            and np.array_equal(crcs_p, crcs_o)
    stats = ec_pipeline.stats()
    lanes_used = sum(1 for d in stats["devices"].values()
                     if d["dispatches"] > 0)
    sharded_ok = bool(warmed and stats["dev_dispatches"] >= 1
                      and lanes_used >= 2
                      and stats["split_dispatches"] >= 1
                      and stats["active_devices"] == n_dev)
    # zero-copy transfer plane gate: the ONLY bytes a fused encode
    # dispatch reads back are the (S_pad, m, L) parity block + the
    # 4-byte CRC per chunk — never the data shards the host already
    # holds.  With every dispatch a warm device dispatch, the H2D and
    # D2H totals obey the exact integer identity
    #   d2h * (k*L) == h2d * (m*L + 4*(k+m))
    # (both sides proportional to the same padded-stripe total); a
    # data-shard echo would inflate d2h by k/m and break it.
    h2d_bytes = stats["bytes_h2d"] - bytes0["bytes_h2d"]
    d2h_bytes = stats["bytes_d2h"] - bytes0["bytes_d2h"]
    readback_ok = bool(
        h2d_bytes > 0
        and d2h_bytes * (k * chunk)
        == h2d_bytes * (m * chunk + 4 * (k + m)))
    # HBM stripe cache gate: encode with a cache intent, commit, then
    # serve a deep-scrub-style CRC fold and a recovery-style payload
    # fetch from the cache — bit-exact vs the host oracle and with
    # ZERO bytes re-uploaded (h2d delta stays 0 through the whole
    # cached phase)
    from ceph_tpu.ops import hbm_cache
    from ceph_tpu.osd import ecutil
    hbm_cache.configure(64 << 20)
    cached = []
    for i in range(4):
        op = rng.integers(0, 256, size=(1, k, chunk), dtype=np.uint8)
        intent = hbm_cache.CacheIntent("smoke.pg", f"obj{i}",
                                       (1, i + 1), k * chunk, chunk)
        h = codec.encode_stripes_with_crcs_async(op, cache=intent)
        h.result(60)
        hbm_cache.get().commit("smoke.pg", f"obj{i}", (1, i + 1))
        cached.append((op, intent))
    cstats0 = ec_pipeline.stats()
    cache_scrub_ok = True
    for i, (op, intent) in enumerate(cached):
        ent = hbm_cache.get().lookup("smoke.pg", f"obj{i}",
                                     version=(1, i + 1))
        if ent is None:
            cache_scrub_ok = False
            continue
        # deep-scrub fold from cached per-stripe chunk CRCs
        folds = ecutil.fold_shard_crcs(ent.crcs, chunk)
        _allc_o, crcs_o = oracle.encode_stripes_with_crcs(op)
        cache_scrub_ok = cache_scrub_ok and \
            folds == ecutil.fold_shard_crcs(np.asarray(crcs_o), chunk)
        # recovery-style payload fetch straight from HBM
        cache_scrub_ok = cache_scrub_ok and \
            ent.data_bytes() == op.tobytes()
    cstats1 = ec_pipeline.stats()
    cache_h2d_bytes = cstats1["bytes_h2d"] - cstats0["bytes_h2d"]
    cache_hits = cstats1["cache_hit"] - cstats0["cache_hit"]
    cache_scrub_ok = bool(cache_scrub_ok and cache_h2d_bytes == 0
                          and cache_hits >= len(cached))
    # mesh-dispatch gate: a payload whose staged bytes exceed a single
    # lane's budget dispatches as ONE shard_mapped batch across the
    # 8-device mesh, with the staging arena DONATED (the ec.stage copy
    # becomes the H2D upload) — previously undispatchable on an
    # HBM-bounded rig.  Gates: >= 1 mesh dispatch, bit-exact vs the
    # host oracle codec, and host_copies_per_write <= 2 on the donated
    # path (shard_layout only, plus slack for a cold-warm stage note).
    from ceph_tpu.utils import copyaudit as _mca
    MESH_COPY_BUDGET = 2.0
    mesh_budget = 256 * 1024
    ec_pipeline.configure(mesh_min_bytes=mesh_budget)
    sinfo_m = ecutil.StripeInfo(k, chunk)     # stripe width 32 KiB
    mesh_pay = rng.integers(
        0, 256, size=12 * k * chunk - 1234,   # ~384 KiB, odd tail
        dtype=np.uint8).tobytes()
    mesh_ok = False
    mesh_copies_per_write = None
    mesh_disp = 0
    mesh_donations = 0
    mstats0 = ec_pipeline.stats()
    mend = time.time() + 120
    while time.time() < mend:               # mesh fn warms in background
        shards_m, _mcrcs = ecutil.encode_object_ex(codec, sinfo_m,
                                                   mesh_pay)
        mst = ec_pipeline.stats()
        if mst["mesh_dispatches"] - mstats0["mesh_dispatches"] >= 1:
            break
        time.sleep(0.25)
    mst = ec_pipeline.stats()
    mesh_disp = mst["mesh_dispatches"] - mstats0["mesh_dispatches"]
    mesh_donations = mst["arena_donations"] - \
        mstats0["arena_donations"]
    if mesh_disp >= 1:
        shards_o, _ocrcs = ecutil.encode_object_ex(oracle, sinfo_m,
                                                   mesh_pay)
        mesh_exact = all(bytes(a) == bytes(b)
                         for a, b in zip(shards_m, shards_o))
        # donated-path copy floor: warm mesh writes pay ONLY the
        # shard-major layout (the staging copy rode the donation)
        mc0 = _mca.snapshot()
        for _ in range(4):
            ecutil.encode_object_ex(codec, sinfo_m, mesh_pay)
        mc1 = _mca.snapshot()
        mesh_copies_per_write = (mc1["host_copies"]
                                 - mc0["host_copies"]) / 4
        mesh_ok = bool(mesh_exact and mesh_donations >= 1
                       and mesh_copies_per_write <= MESH_COPY_BUDGET)
    mst = ec_pipeline.stats()
    log(f"smoke mesh: {mesh_disp} mesh dispatches, "
        f"{mst['arena_donations'] - mstats0['arena_donations']} arena "
        f"donations, copies/write="
        f"{mesh_copies_per_write if mesh_copies_per_write is not None else 'n/a'}"
        f" (budget {MESH_COPY_BUDGET}), mesh table={mst['mesh']}, "
        f"ok={mesh_ok}")
    # quarantine drill: fault ONE chip of the mesh, keep encoding —
    # the lane quarantines, work redrains to survivors bit-exactly,
    # and the codec must NOT degrade
    faults.get().tpu_device_error(1.0, device="0")
    qops = [rng.integers(0, 256, size=(1, k, chunk), dtype=np.uint8)
            for _ in range(8)]
    qhandles = [codec.encode_stripes_with_crcs_async(op)
                for op in qops]
    for op, h in zip(qops, qhandles):
        allc_q, crcs_q = h.result(60)
        allc_o, crcs_o = oracle.encode_stripes_with_crcs(op)
        ok = ok and np.array_equal(allc_q, allc_o) \
            and np.array_equal(crcs_q, crcs_o)
    faults.get().reset()
    qstats = ec_pipeline.stats()
    quarantine_ok = bool(qstats["quarantines"] >= 1
                         and qstats["devices"]["0"]["quarantined"]
                         and qstats["active_devices"] == n_dev - 1
                         and not codec.degraded)
    # zero-copy host-path gate: drive writes through the production
    # rope -> encode-stage -> shard-view fan-out -> store pipeline and
    # pin the host copies per write.  The budget is the two designed
    # materializations (encode staging + shard-major layout, see
    # utils/copyaudit.py) with one spare for a journaled store's WAL
    # flatten — a regression that re-introduces per-hop copies
    # (per-shard bytes, denc payload echo, rope flattens) blows
    # through it and fails CI.
    from ceph_tpu import native as _native
    from ceph_tpu.store.memstore import MemStore
    from ceph_tpu.store.objectstore import Transaction
    from ceph_tpu.utils import copyaudit
    from ceph_tpu.utils.bufferlist import BufferList
    COPY_BUDGET = 3.0
    cstore = MemStore()
    cstore.apply_transaction(Transaction().create_collection("smoke"))
    sinfo = ecutil.StripeInfo(k, chunk)
    ncw = 8
    copy0 = copyaudit.snapshot()
    for i in range(ncw):
        pay = BufferList(rng.integers(0, 256, size=3 * chunk,
                                      dtype=np.uint8).tobytes())
        pay.append(b"tail" * 64)
        shards, _crcs = ecutil.encode_object_ex(oracle, sinfo, pay)
        txn = Transaction()
        for shard, sdata in enumerate(shards):
            txn.truncate("smoke", f"c{i}.s{shard}", 0)
            txn.write("smoke", f"c{i}.s{shard}", 0, sdata)
        cstore.apply_transaction(txn)
    copy1 = copyaudit.snapshot()
    host_copies_per_write = (copy1["host_copies"]
                             - copy0["host_copies"]) / ncw
    copy_ok = bool(host_copies_per_write <= COPY_BUDGET)
    # serving-plane mini row: a seeded open-loop load burst against a
    # real 3-osd cluster gates tail-latency sanity and the READ-side
    # copy floor (host_copies_per_read) the same way the write gate
    # above pins host_copies_per_write
    ec_pipeline.get().reset_devices()    # clear the quarantine latch
    from ceph_tpu.tools.loadgen import LoadGen, TenantSpec
    from ceph_tpu.utils import copyaudit as _ca
    READ_COPY_BUDGET = 1.0
    P99_SANITY_MS = 2000.0
    load_p99 = None
    load_copies_per_read = None
    load_errors = -1
    load_ok = False
    peering_ms_1x = peering_ms_10x = None
    peering_flat_ok = False
    try:
        cluster = _load_cluster()
        try:
            lrados = cluster.client()
            lio = _settle_pool(lrados, "smoke-load", "smokep")
            gen = LoadGen([TenantSpec(
                "smoke-load", rate=80, duration=2.0, obj_count=16,
                zipf_s=1.1, read_frac=0.6, payload=8192,
                append_frac=0.1)], seed=0x510AD)
            c0 = _ca.snapshot()
            rep = gen.run({"smoke-load": lio})
            c1 = _ca.snapshot()
            lreads = max(1, c1["reads"] - c0["reads"])
            load_copies_per_read = (c1["read_copies"]
                                    - c0["read_copies"]) / lreads
            load_p99 = rep["p99_ms"]
            load_errors = sum(p["errors"]
                              for p in rep["pools"].values())
            load_ok = bool(load_p99 < P99_SANITY_MS
                           and load_copies_per_read
                           <= READ_COPY_BUDGET
                           and load_errors == 0
                           and rep["completed"]
                           == sum(rep["offered"].values()))
            log(f"smoke load: p99={load_p99}ms (sanity "
                f"{P99_SANITY_MS:.0f}), copies/read="
                f"{load_copies_per_read:.2f} (budget "
                f"{READ_COPY_BUDGET}), errors={load_errors}, "
                f"ok={load_ok}")
            # log-authoritative peering flatness gate: a full peering
            # round exchanges log BOUNDS only, so its wall time at 10x
            # the object count must stay flat — an O(objects) term
            # creeping back into the info/election/recovery path
            # fails CI here
            lrados.create_pool("smoke-peer", pg_num=1, size=3,
                               min_size=2)
            pio = lrados.open_ioctx("smoke-peer")
            pend = time.time() + 30
            while True:
                try:
                    pio.write_full("settle", b"s")
                    break
                except Exception:
                    if time.time() > pend:
                        raise
                    time.sleep(0.3)
            pm = cluster.leader().osdmon.osdmap
            ppgid = pm.object_to_pg(pio.pool_id, "settle")
            for i in range(8):
                pio.write_full(f"o{i:04d}", b"x" * 64)
            peering_ms_1x = _measure_peering_ms(cluster, ppgid,
                                                reps=3)
            for i in range(8, 80):
                pio.write_full(f"o{i:04d}", b"x" * 64)
            peering_ms_10x = _measure_peering_ms(cluster, ppgid,
                                                 reps=3)
            peering_flat_ok = bool(
                peering_ms_1x is not None
                and peering_ms_10x is not None
                and peering_ms_10x <= 2.0 * peering_ms_1x + 25.0)
            log(f"smoke peering: {peering_ms_1x} ms @ 8 objs vs "
                f"{peering_ms_10x} ms @ 80 objs, flat_ok="
                f"{peering_flat_ok}")
        finally:
            cluster.stop()
    except Exception as e:
        log(f"smoke load harness FAILED: {type(e).__name__}: {e}")
    # op tracing plane: the tracer-overhead gate.  The SAME seeded
    # mini load round runs with the op tracker off and on against one
    # cluster whose per-op service time is pinned by the injected
    # dispatch delay (so the tracer's per-op microseconds are judged
    # against a deterministic baseline, not scheduler noise) — p99
    # and goodput with tracing on must stay within 5% of tracing-off,
    # or the plane is too expensive to leave on.  Best-of-2 per mode:
    # a one-off scheduler hiccup is noise, a systematic cost is not.
    TRACE_DELTA = 0.05
    trace_p99_on = trace_p99_off = None
    trace_good_on = trace_good_off = None
    trace_phases = None
    trace_overhead_ok = False
    try:
        ec_pipeline.get().reset_devices()
        cluster = _load_cluster({
            "osd_debug_inject_dispatch_delay_probability": 1.0,
            "osd_debug_inject_dispatch_delay_duration": 0.02,
            "osd_op_history_size": 512,
        })
        try:
            trados = cluster.client()
            tio = _settle_pool(trados, "smoke-trace", "smoketr")
            trackers = [o.op_tracker for o in cluster.osds.values()]

            def trace_round(enabled: bool) -> dict:
                for osd in cluster.osds.values():
                    osd.op_tracker.enabled = enabled
                gen = LoadGen([TenantSpec(
                    "smoke-trace", rate=40, duration=2.0,
                    obj_count=16, zipf_s=1.1, read_frac=0.5,
                    payload=8192)], seed=0x7ACE)
                return gen.run(
                    {"smoke-trace": tio},
                    phase_sources=trackers if enabled else None)

            reps = {False: [], True: []}
            # interleaved off/on rounds so machine drift hits both;
            # best-of-3 per mode keeps a single scheduler excursion
            # on a 1-cpu runner from deciding the verdict
            for enabled in (False, True, False, True, False, True):
                reps[enabled].append(trace_round(enabled))
            trace_p99_off = min(r["p99_ms"] for r in reps[False])
            trace_p99_on = min(r["p99_ms"] for r in reps[True])
            trace_good_off = max(r["goodput_gbs"] for r in reps[False])
            trace_good_on = max(r["goodput_gbs"] for r in reps[True])
            trace_phases = next(
                (r.get("phases") for r in reps[True]
                 if r.get("phases")), None)
            errs = sum(p["errors"] for r in reps[False] + reps[True]
                       for p in r["pools"].values())
            trace_overhead_ok = bool(
                errs == 0
                and trace_p99_off > 0 and trace_good_off > 0
                and trace_p99_on <= trace_p99_off * (1 + TRACE_DELTA)
                and trace_good_on >= trace_good_off * (1 - TRACE_DELTA)
                # the traced round really traced: the breakdown saw
                # queue + execute spans on the daemons
                and trace_phases is not None
                and "queue" in trace_phases
                and "execute" in trace_phases)
            log(f"smoke trace overhead: p99 {trace_p99_off}ms off vs "
                f"{trace_p99_on}ms on, goodput {trace_good_off} vs "
                f"{trace_good_on} GB/s (budget {TRACE_DELTA:.0%}), "
                f"phases={sorted(trace_phases or {})}, "
                f"ok={trace_overhead_ok}")
        finally:
            cluster.stop()
    except Exception as e:
        log(f"smoke trace-overhead gate FAILED: "
            f"{type(e).__name__}: {e}")
    # serve-during-repair: the mini seeded recovery-storm gate — a
    # 3-OSD cluster takes one abrupt OSD kill + rebirth UNDER open-loop
    # load.  Gates: zero client errors, zero stale-byte reads (verify
    # oracle), every recovery-blocked op resumed (counter-balanced),
    # the ledger stream bit-exact through the storm, the reserved
    # pool's p99 bounded, and recovery actually completing.
    STORM_P99_BOUND_MS = 8000.0
    storm_p99 = storm_recovery_s = None
    storm_errors = storm_stale = -1
    storm_blocked = storm_unblocked = storm_promotions = -1
    storm_ok = False
    try:
        ec_pipeline.get().reset_devices()
        from ceph_tpu.tools.loadgen import (TenantSpec,
                                            run_recovery_storm)
        cluster = _load_cluster({
            "osd_qos_recovery": "0:2:0",
            "osd_pool_qos_gold": "40:4:0",
            "objecter_op_timeout": 60.0,
        })
        try:
            ios = _storm_pools(cluster)
            tenants = [
                TenantSpec("gold", rate=30, duration=6.0,
                           obj_count=16, zipf_s=1.1, read_frac=0.6,
                           payload=8192),
                TenantSpec("bulk", rate=15, duration=6.0,
                           obj_count=16, zipf_s=0.9, read_frac=0.3,
                           payload=16384),
            ]
            res = run_recovery_storm(cluster, ios, tenants,
                                     seed=0x570A, kill_at=1.5,
                                     revive_after=1.2,
                                     clean_timeout=120.0)
            gold_storm = res["storm"].get("gold", {})
            storm_p99 = gold_storm.get("p99_ms")
            storm_errors = res["errors"]
            storm_stale = res["stale_reads"]
            storm_blocked = res["recovery_blocked_ops"]
            storm_unblocked = res["recovery_unblocked_ops"]
            storm_promotions = res["recovery_prio_promotions"]
            storm_recovery_s = res["recovery_wall_s"]
            storm_ok = bool(
                res["ledger_ok"]
                and storm_errors == 0
                and storm_stale == 0
                and storm_blocked == storm_unblocked
                and storm_p99 is not None
                and storm_p99 < STORM_P99_BOUND_MS
                and storm_recovery_s is not None)
            log(f"smoke storm: gold storm p99={storm_p99}ms (bound "
                f"{STORM_P99_BOUND_MS:.0f}), errors={storm_errors}, "
                f"stale={storm_stale}, blocked={storm_blocked}/"
                f"unblocked={storm_unblocked}, promotions="
                f"{storm_promotions}, recovery="
                f"{storm_recovery_s}s, ledger_ok={res['ledger_ok']}, "
                f"ok={storm_ok}")
        finally:
            cluster.stop()
    except Exception as e:
        log(f"smoke recovery-storm gate FAILED: "
            f"{type(e).__name__}: {e}")
    # front doors under fire: one seeded schedule mixing raw rados,
    # S3 over real HTTP, CephFS and RBD against a 3-OSD cluster while
    # the drill partitions the two RGW zones, deletes through the
    # primary mid-split, crashes the secondary gateway and
    # kills+rebirths an OSD.  Gates: zero errors, zero stale reads at
    # EVERY door, the two-zone ledger clean (acked puts bit-exact at
    # the replica, the partitioned delete never resurrects), and the
    # sync agent's counters showing backoff-not-wedge.
    fd_errors = fd_stale = -1
    fd_zone_ok = False
    fd_sync_errors = fd_backoff = fd_doors = None
    frontdoor_ok = False
    try:
        ec_pipeline.get().reset_devices()
        from ceph_tpu.rgw.sync import RGWSyncAgent
        from ceph_tpu.tools.loadgen import run_frontdoor_storm
        cluster = _load_cluster({"objecter_op_timeout": 5.0})
        try:
            fd = _frontdoor_doors(cluster)
            gw_a = fd["gateway"]
            gw_b = cluster.start_rgw(data_pool="zone_b")
            agent = RGWSyncAgent(gw_b,
                                 f"http://127.0.0.1:{gw_a.port}",
                                 interval=0.2).start()

            def respawn():
                gw2 = cluster.start_rgw(port=gw_b.port,
                                        data_pool="zone_b")
                ag2 = RGWSyncAgent(gw2,
                                   f"http://127.0.0.1:{gw_a.port}",
                                   interval=0.2).start()
                return gw2, ag2

            zones = {"primary": gw_a, "secondary": gw_b,
                     "agent": agent, "respawn": respawn}
            res = run_frontdoor_storm(
                cluster, fd["ioctxs"], _frontdoor_tenants(4.0),
                zones=zones, seed=0xD00D)
            zones["agent"].shutdown()
            fd["image"].close()
            fd_errors = res["errors"]
            fd_stale = res["stale_reads"]
            fd_zone_ok = res["zone_ledger_ok"]
            fd_sync_errors = res["sync"].get("sync_errors", 0)
            fd_backoff = round(
                res["sync"].get("sync_backoff_secs", 0.0), 3)
            fd_doors = sorted(res["doors"])
            frontdoor_ok = bool(
                fd_errors == 0 and fd_stale == 0 and fd_zone_ok
                and fd_doors == ["cephfs", "rados", "rbd", "s3"]
                and fd_sync_errors > 0 and fd_backoff > 0)
            log(f"smoke frontdoor: doors={fd_doors}, "
                f"errors={fd_errors}, stale={fd_stale}, "
                f"zone_ledger_ok={fd_zone_ok}, sync_errors="
                f"{fd_sync_errors}, backoff={fd_backoff}s, "
                f"ok={frontdoor_ok}")
        finally:
            cluster.stop()
    except Exception as e:
        log(f"smoke frontdoor gate FAILED: {type(e).__name__}: {e}")
    # async serving plane: the high-fan-in gate — 256 full client
    # sessions (messenger + monc + objecter each) ALL open at once
    # against one ms_type=async cluster.  Gates: zero op errors,
    # every scheduled op completed, peak thread growth bounded by the
    # storm's own driver pool (sessions multiplex onto the fixed
    # epoll worker pool — per-session threads would read as linear
    # growth here), tail sane, and the churn residue zero: threads
    # AND fds back to the pre-storm baseline after every session
    # closes.
    CONN_SESSIONS = 256
    CONN_P99_BOUND_MS = 5000.0
    CONN_DRIVER_THREADS = 32
    conn_p99 = conn_goodput = None
    conn_errors = -1
    conn_base_threads = conn_peak_threads = conn_quiesce_threads = None
    conn_base_fds = conn_peak_fds = conn_quiesce_fds = None
    conn_event_workers = None
    conn_ok = False
    try:
        ec_pipeline.get().reset_devices()
        from ceph_tpu.tools.loadgen import run_conn_storm
        cluster = _load_cluster({"ms_type": "async"})
        try:
            cres = run_conn_storm(cluster, CONN_SESSIONS,
                                  seed=0xC044,
                                  driver_threads=CONN_DRIVER_THREADS)
            conn_p99 = cres["p99_ms"]
            conn_goodput = cres["goodput_mbs"]
            conn_errors = cres["errors"]
            conn_base_threads = cres["base_threads"]
            conn_peak_threads = cres["peak_threads"]
            conn_quiesce_threads = cres["quiesce_threads"]
            conn_base_fds = cres["base_fds"]
            conn_peak_fds = cres["peak_fds"]
            conn_quiesce_fds = cres["quiesce_fds"]
            conn_event_workers = cres["event_workers"]
            conn_ok = bool(
                conn_errors == 0
                and cres["completed"] == cres["expected"]
                and cres["ms_type"] == "async"
                and conn_p99 < CONN_P99_BOUND_MS
                and conn_peak_threads - conn_base_threads
                <= CONN_DRIVER_THREADS + 16
                and conn_quiesce_threads <= conn_base_threads
                and conn_quiesce_fds <= conn_base_fds)
            log(f"smoke conn: {CONN_SESSIONS} async sessions, "
                f"p99={conn_p99}ms (bound {CONN_P99_BOUND_MS:.0f}), "
                f"goodput={conn_goodput}MB/s, errors={conn_errors}, "
                f"threads {conn_base_threads}->{conn_peak_threads}"
                f"->{conn_quiesce_threads}, fds {conn_base_fds}->"
                f"{conn_peak_fds}->{conn_quiesce_fds}, workers="
                f"{conn_event_workers}, ok={conn_ok}")
        finally:
            cluster.stop()
    except Exception as e:
        log(f"smoke conn gate FAILED: {type(e).__name__}: {e}")
    ok = (ok and sharded_ok and quarantine_ok and readback_ok
          and cache_scrub_ok and copy_ok and load_ok
          and peering_flat_ok and mesh_ok and trace_overhead_ok
          and storm_ok and frontdoor_ok and conn_ok)
    log(f"smoke: host {host_gbs:.2f} GB/s, e2e serial "
        f"{serial_gbs:.3f} GB/s, pipelined {pipe_gbs:.3f} GB/s, "
        f"{stats['dispatches']} dispatches "
        f"(mean batch {stats['mean_batch_size']:.1f}), "
        f"{lanes_used}/{n_dev} lanes used, "
        f"{stats['split_dispatches']} splits, sharded_ok="
        f"{sharded_ok}, readback_ok={readback_ok} "
        f"({h2d_bytes} B h2d / {d2h_bytes} B d2h), cache_scrub_ok="
        f"{cache_scrub_ok} ({cache_hits} hits, {cache_h2d_bytes} B "
        f"h2d while cached), quarantine_ok={quarantine_ok}, "
        f"copies/write={host_copies_per_write:.1f} (budget "
        f"{COPY_BUDGET}, ok={copy_ok}), ok={ok}")
    print(json.dumps({
        "metric": "bench_smoke", "smoke": True, "ok": bool(ok),
        "host_copies_per_write": round(host_copies_per_write, 2),
        "copy_budget": COPY_BUDGET,
        "copy_ok": copy_ok,
        "crc_hw": bool(_native.crc32c_hw()),
        "host_avx2_gbs": round(host_gbs, 3),
        "e2e_serial_gbs": round(serial_gbs, 4),
        "e2e_pipelined_gbs": round(pipe_gbs, 4),
        "pipeline_dispatches": stats["dispatches"],
        "pipeline_mean_batch": round(stats["mean_batch_size"], 2),
        "devices": n_dev,
        "lanes_used": lanes_used,
        "split_dispatches": stats["split_dispatches"],
        "sharded_ok": sharded_ok,
        "bytes_h2d": h2d_bytes,
        "bytes_d2h": d2h_bytes,
        "readback_ok": readback_ok,
        "cache_hits": cache_hits,
        "cache_h2d_bytes": cache_h2d_bytes,
        "cache_scrub_ok": cache_scrub_ok,
        "quarantines": qstats["quarantines"],
        "active_after_quarantine": qstats["active_devices"],
        "quarantine_ok": quarantine_ok,
        "mesh_dispatches": mesh_disp,
        "arena_donations": mesh_donations,
        "mesh_copies_per_write": (
            round(mesh_copies_per_write, 2)
            if mesh_copies_per_write is not None else None),
        "mesh_copy_budget": MESH_COPY_BUDGET,
        "mesh_ok": mesh_ok,
        "load_p99_ms": load_p99,
        "load_errors": load_errors,
        "host_copies_per_read": (
            round(load_copies_per_read, 2)
            if load_copies_per_read is not None else None),
        "read_copy_budget": READ_COPY_BUDGET,
        "load_ok": load_ok,
        "peering_ms_at_1x": (round(peering_ms_1x, 2)
                             if peering_ms_1x is not None else None),
        "peering_ms_at_10x": (round(peering_ms_10x, 2)
                              if peering_ms_10x is not None else None),
        "peering_flat_ok": peering_flat_ok,
        "trace_p99_off_ms": trace_p99_off,
        "trace_p99_on_ms": trace_p99_on,
        "trace_goodput_off_gbs": trace_good_off,
        "trace_goodput_on_gbs": trace_good_on,
        "trace_phases": sorted(trace_phases) if trace_phases else None,
        "trace_overhead_ok": trace_overhead_ok,
        "storm_p99_ms": storm_p99,
        "storm_p99_bound_ms": STORM_P99_BOUND_MS,
        "storm_errors": storm_errors,
        "storm_stale_reads": storm_stale,
        "storm_blocked_ops": storm_blocked,
        "storm_unblocked_ops": storm_unblocked,
        "storm_promotions": storm_promotions,
        "storm_recovery_s": storm_recovery_s,
        "storm_ok": storm_ok,
        "frontdoor_errors": fd_errors,
        "frontdoor_stale_reads": fd_stale,
        "frontdoor_zone_ledger_ok": fd_zone_ok,
        "frontdoor_sync_errors": fd_sync_errors,
        "frontdoor_sync_backoff_secs": fd_backoff,
        "frontdoor_doors": fd_doors,
        "frontdoor_ok": frontdoor_ok,
        "conn_sessions": CONN_SESSIONS,
        "conn_p99_ms": conn_p99,
        "conn_p99_bound_ms": CONN_P99_BOUND_MS,
        "conn_goodput_mbs": conn_goodput,
        "conn_errors": conn_errors,
        "conn_event_workers": conn_event_workers,
        "conn_base_threads": conn_base_threads,
        "conn_peak_threads": conn_peak_threads,
        "conn_quiesce_threads": conn_quiesce_threads,
        "conn_base_fds": conn_base_fds,
        "conn_peak_fds": conn_peak_fds,
        "conn_quiesce_fds": conn_quiesce_fds,
        "conn_ok": conn_ok,
    }))
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(0 if ok else 1)


def main() -> None:
    if "--smoke" in sys.argv:
        bench_smoke()
        return
    if "--load" in sys.argv:
        # standalone serving-plane run: open-loop multi-tenant load +
        # the cache-served read row + the connection-count sweep,
        # one JSON line
        rows = []
        fast = bool(os.environ.get("BENCH_FAST"))
        load = bench_load(rows, fast=fast)
        conn = bench_conn_scaling(rows, fast=fast)
        log("workload | plugin | k | m | chunk | GB/s-or-ms")
        for w, p, k, m, c, g in rows:
            log(f"{w} | {p} | {k} | {m} | {c} | {g:.3f}")
        print(json.dumps({"metric": "load_harness", **{
            f"load_{k2}": v for k2, v in load.items()}, **conn}))
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(0)
    if "--recovery-slo" in sys.argv:
        # standalone serve-during-repair sweep: the seeded OSD-kill
        # storm under load at >= 2 osd_qos_recovery settings — client
        # p99 during the storm vs recovery wall time, one JSON line
        slo = bench_recovery_slo(fast=bool(os.environ.get("BENCH_FAST")))
        log("setting | gold storm p99 ms | recovery s | blocked")
        for row in slo["sweep"]:
            log(f"{row['osd_qos_recovery']} | "
                f"{row['gold_storm_p99_ms']} | "
                f"{row['recovery_wall_s']} | {row['blocked_ops']}")
        print(json.dumps({"metric": "recovery_slo", **slo}))
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(0)
    if "--peering" in sys.argv:
        # standalone log-authoritative peering sweep: wall-time
        # flatness at 1x/10x/100x object counts + the
        # recovery-bytes-∝-divergence drill, one JSON line
        rows = []
        peering = bench_peering(rows,
                                fast=bool(os.environ.get("BENCH_FAST")))
        log("workload | plugin | k | m | objects | ms")
        for w, p, k, m, c, g in rows:
            log(f"{w} | {p} | {k} | {m} | {c} | {g:.3f}")
        print(json.dumps({"metric": "peering_scaling", **peering}))
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(0)
    if "--multichip" in sys.argv:
        # standalone multichip sweep (1/2/4/8 chips as available):
        # aggregate + per-chip GB/s and scaling efficiency
        rows: list = []
        fast = bool(os.environ.get("BENCH_FAST"))
        mc = bench_multichip(
            rows, chunk=4096 if fast else 1 << 20,
            nops=16 if fast else 32,
            warm_window=60.0 if fast else 240.0)
        log("workload | plugin | k | m | chunk | GB/s")
        for w, p, k, m, c, g in rows:
            log(f"{w} | {p} | {k} | {m} | {c} | {g:.3f}")
        print(json.dumps({"metric": "ec_multichip_scaling",
                          "chips": mc}))
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(0)
    rows = []
    results: list = []
    fast = bool(os.environ.get("BENCH_FAST"))

    def _section(name, fn, default=None):
        # one failing section must never cost the driver the whole
        # JSON record (BENCH_r05 regression: the final line lost
        # e2e_pipelined_gbs) — every headline key is ALWAYS emitted,
        # null when its section failed
        try:
            return fn()
        except Exception as e:
            log(f"bench section {name} FAILED: "
                f"{type(e).__name__}: {e}")
            return default

    primary = _section("config2", lambda: bench_config2(results, rows))
    e2e = _section("e2e", lambda: bench_e2e(rows))
    e2e_gbs = e2e["serial"] if e2e else None
    # per-hop host-path breakdown: stripe/frame/fanout/store wall µs +
    # bytes copied per hop, so the next bottleneck is a NAMED hop
    host_path = _section("host_path_breakdown",
                         lambda: bench_host_path_breakdown(rows))
    # headline pipelined row = PRODUCTION measured routing (the
    # cluster write path's real plane selection); fast mode keeps it
    # but trims the op count and warm-up window
    pipelined = _section("e2e_pipelined", lambda: bench_e2e_pipelined(
        rows, nops=8 if fast else 32,
        warm_window=60.0 if fast else 240.0))
    # device-plane tracking row: the old forced-device methodology
    pipelined_dev = None
    if not fast:
        pipelined_dev = _section(
            "e2e_pipelined_dev", lambda: bench_e2e_pipelined(
                rows, nops=16, warm_window=120.0, routing="device"))
    breakdown = _section("transfer_breakdown",
                         lambda: bench_transfer_breakdown(rows))
    # serving plane: open-loop multi-tenant load + cache-served reads
    # (fast mode trims duration/object counts, never the row set —
    # the BENCH trajectory tracks these keys from r06 on)
    load = _section("load", lambda: bench_load(rows, fast=fast))
    # control plane: peering wall-time flatness + recovery ∝ divergence
    peering = _section("peering", lambda: bench_peering(rows, fast=fast))
    crossover = {"store": None, "scrub": None}
    multichip = None
    if not fast:
        crossover = _section("crossover",
                             lambda: bench_crossover(rows),
                             default={"store": None, "scrub": None})
        _section("other_configs", lambda: bench_other_configs(rows))

        def _mc():
            import jax
            if len(jax.devices()) > 1:
                # multi-device rig: sweep chip counts (single-chip
                # rigs run the sweep via `bench.py --multichip` on
                # the CPU mesh, or skip — a 1-point sweep says
                # nothing)
                return bench_multichip(rows)
            return None

        multichip = _section("multichip", _mc)
    # the router's own amortized estimate (EMA bucket granularity, from
    # the pipelined run's coalesced batches) is reported as its OWN
    # field — a different methodology than the sweep's exact payloads,
    # so it must not masquerade as crossover_store_bytes

    log("workload | plugin | k | m | chunk | GB/s")
    for w, p, k, m, c, g in rows:
        log(f"{w} | {p} | {k} | {m} | {c} | {g:.3f}")

    def _r(x, nd=3):
        return round(x, nd) if x is not None else None

    def _crc_hw():
        try:
            from ceph_tpu import native
            return bool(native.crc32c_hw())
        except Exception:
            return False

    def _mesh_key(mc, key):
        """`key` from the largest swept chip count that has it."""
        if not mc:
            return None
        rows_by_n = sorted(((int(n), row) for n, row in mc.items()
                            if n.isdigit()
                            and row.get(key) is not None),
                           reverse=True)
        return rows_by_n[0][1][key] if rows_by_n else None

    print(json.dumps({
        "metric": "ec_fused_encode_crc_rs_k8m3_1MiB",
        "value": _r(primary["enc"]) if primary else None,
        "unit": "GB/s",
        "vs_baseline": _r(primary["enc"] / primary["host"], 2)
        if primary else None,
        "decode_gbs": _r(primary["dec"]) if primary else None,
        "host_avx2_gbs": _r(primary["host"]) if primary else None,
        "e2e_gbs": _r(e2e_gbs),
        "e2e_overlap_gbs": _r(e2e["overlap"]) if e2e else None,
        "e2e_overlap_efficiency": e2e.get("overlap_efficiency")
        if e2e else None,
        # primary e2e metric: pipelined through the PRODUCTION
        # measured routing (coalesced + overlapped + zero-copy host
        # plane; the router picks the winning plane per dispatch)
        "e2e_pipelined_gbs": _r(pipelined["gbs"]) if pipelined
        else None,
        "e2e_pipelined_routing": pipelined["routing"] if pipelined
        else None,
        "e2e_pipelined_dev_dispatches": pipelined["dev_dispatches"]
        if pipelined else None,
        "e2e_pipelined_dev_gbs": _r(pipelined_dev["gbs"])
        if pipelined_dev else None,
        "e2e_pipelined_vs_serial": _r(
            pipelined["gbs"] / max(e2e_gbs, 1e-9), 2)
        if pipelined and e2e_gbs else None,
        "pipelined_bytes_h2d": pipelined["bytes_h2d"]
        if pipelined else None,
        "pipelined_bytes_d2h": pipelined["bytes_d2h"]
        if pipelined else None,
        "transfer_breakdown": breakdown,
        "host_path_breakdown": host_path,
        "host_copies_per_write": (
            round(sum(h.get("copies", 0) for name, h in
                      host_path.items() if name != "total"), 1)
            if host_path else None),
        "crc_hw": _crc_hw(),
        # serving plane (open-loop harness + cache-served reads)
        "load_p50_ms": load["p50_ms"] if load else None,
        "load_p99_ms": load["p99_ms"] if load else None,
        "load_p999_ms": load["p999_ms"] if load else None,
        "load_goodput_gbs": load["goodput_gbs"] if load else None,
        "load_pools": load["pools"] if load else None,
        "host_copies_per_read": load["host_copies_per_read"]
        if load else None,
        "read_cache_gbs": load["read_cache_gbs"] if load else None,
        "read_store_gbs": load["read_store_gbs"] if load else None,
        # log-authoritative peering plane
        "peering_ms_at_1x": peering.get("peering_ms_at_1x")
        if peering else None,
        "peering_ms_at_10x": peering.get("peering_ms_at_10x")
        if peering else None,
        "peering_ms_at_100x": peering.get("peering_ms_at_100x")
        if peering else None,
        "recovery_bytes_per_divergent_entry": peering.get(
            "recovery_bytes_per_divergent_entry") if peering else None,
        "recovery_proportional_ok": peering.get(
            "recovery_proportional_ok") if peering else None,
        "crossover_store_bytes": crossover["store"],
        "crossover_scrub_bytes": crossover["scrub"],
        "router_crossover_store_bytes": pipelined["crossover"]
        if pipelined else None,
        "multichip": multichip,
        # pod-scale mesh headline keys (always emitted; null when the
        # rig has one device or the sweep was skipped): the biggest
        # swept mesh's aggregate GB/s + efficiency vs 1-chip
        # row-split, and the object-larger-than-one-lane's-budget
        # case that only mesh dispatch can serve
        "mesh_aggregate_gbs": _mesh_key(multichip,
                                        "mesh_aggregate_gbs"),
        "mesh_scaling_efficiency": _mesh_key(
            multichip, "mesh_scaling_efficiency"),
        "mesh_mega_object_gbs": (multichip or {}).get(
            "mega_object", {}).get("gbs"),
        "mesh_mega_object_ok": (multichip or {}).get(
            "mega_object", {}).get("ok"),
    }))
    sys.stdout.flush()
    sys.stderr.flush()
    # background jit-warm threads (TpuBackend) may still be inside a
    # device compile; normal interpreter teardown aborts the process
    # ("FATAL: exception not rethrown") AFTER the result line — skip
    # teardown so the driver always sees a clean exit
    os._exit(0)


if __name__ == "__main__":
    main()
